// Native LSM storage engine — the role of the reference's RocksDB
// (/root/reference/src/Lachain.Storage/RocksDbContext.cs:23-60: one KV
// store, WAL-synced writes, atomic batches), re-designed small instead of
// vendored. Round-6 rebuild of the write and read paths:
//
//   * memtable: arena-backed skiplist. A batch payload is copied into the
//     arena ONCE; ops are sorted views into that copy and merge into the
//     skiplist with an ascending splice (the search for key i+1 resumes
//     from key i's update path), so bulk trie batches skip the
//     per-key-from-the-top search a std::map paid.
//   * WAL: a dedicated writer thread owns the segment fd. write_batch
//     enqueues the CRC-framed record and applies the memtable while the
//     writer write()+fsync()s concurrently; the ack fires only once the
//     record is durable (persist-before-ack, the contract
//     tests/test_crashpoints.py pins). Records enqueued while an fsync is
//     in flight share the next one — group commit for concurrent callers.
//   * flush: the active memtable seals into an immutable queue and a
//     background flusher streams it into an SST; the WAL rotates to a new
//     segment at each seal, and a segment is unlinked only after every
//     batch in it is durable in an SST + manifest. Replay after a crash
//     may re-apply already-flushed records — harmless, the memtable layer
//     shadows the tables with identical values.
//   * compaction: a rate-limited background worker merges ALL tables
//     (newest wins, tombstones drop — nothing older can resurrect) via
//     streaming cursors; the swap is tmp+rename+manifest-rewrite, and a
//     kill -9 at any point leaves either the old set or the new set
//     manifest-reachable with at most orphan files, which open() removes.
//   * reads: per-SSTable bloom filter + block index live in the table
//     footer; point lookups consult the filter, binary-search the block
//     index and fetch one CRC-checked ~4 KiB block through a shared LRU
//     block cache instead of paying a full per-table key index in memory.
//
// Durability contract (matches SqliteKV's synchronous=FULL batches):
//   * write_batch returns only after its WAL record is fsynced — a batch
//     is all-or-nothing across kill -9 (CRC framing; torn tail of the
//     ACTIVE segment is discarded AND truncated on open).
//   * SST + manifest land via tmp+rename+dir-fsync before any WAL segment
//     covering them is unlinked.
//
// Python binding: storage/lsm.py (ctypes). The batch wire format Python
// sends IS the WAL payload format, so the engine appends it verbatim.
// Debug-only crash surface for the torn-state matrix:
// lsm_write_batch_partial (stop after WAL encode / after fsync, never
// apply) and lsm_compact_partial (merge + rename, no manifest swap).
#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <dirent.h>
#include <fcntl.h>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <string_view>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

typedef uint8_t u8;
typedef uint32_t u32;
typedef uint64_t u64;

// CRC32 (IEEE, table-driven)
static u32 CRC_TAB[256];
static void crc_init() {
  static bool done = false;
  if (done) return;
  done = true;
  for (u32 i = 0; i < 256; i++) {
    u32 c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    CRC_TAB[i] = c;
  }
}
static u32 crc32(const u8* p, size_t n) {
  u32 c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = CRC_TAB[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static void put_u32(std::string& s, u32 v) {
  for (int i = 0; i < 4; i++) s.push_back((char)((v >> (8 * i)) & 0xFF));
}
static u32 get_u32(const u8* p) {
  return (u32)p[0] | ((u32)p[1] << 8) | ((u32)p[2] << 16) | ((u32)p[3] << 24);
}
static void put_u64(std::string& s, u64 v) {
  for (int i = 0; i < 8; i++) s.push_back((char)((v >> (8 * i)) & 0xFF));
}
static u64 get_u64(const u8* p) {
  u64 v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

static bool write_all(int fd, const char* p, size_t n) {
  size_t done = 0;
  while (done < n) {
    ssize_t w = ::write(fd, p + done, n - done);
    if (w <= 0) return false;
    done += (size_t)w;
  }
  return true;
}

static bool fsync_path(const std::string& path) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = ::fsync(fd) == 0;
  ::close(fd);
  return ok;
}

// 64-bit mix hash (splitmix-style avalanche over FNV accumulation) for the
// bloom filter's double hashing: g_i = h1 + i*h2.
static u64 hash64(const void* data, size_t n, u64 seed) {
  const u8* p = (const u8*)data;
  u64 h = seed ^ 0xcbf29ce484222325ull;
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 0x100000001b3ull;
  }
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  h *= 0xc4ceb9fe1a85ec53ull;
  h ^= h >> 33;
  return h;
}

constexpr int BLOOM_BITS_PER_KEY = 10;
constexpr u32 BLOOM_K = 6;
constexpr size_t BLOCK_TARGET = 4096;     // data block payload target
constexpr size_t WRITE_BUF = 1u << 20;    // table builder write coalescing
constexpr size_t IMM_QUEUE_STALL = 4;     // write-path backpressure bound

// batch payload: u32 count, then per op u8 type(0 put/1 del), u32 klen,
// key, u32 vlen, val (vlen=0 for deletes)
struct OpView {
  std::string_view key, val;
  bool del;
  u32 order;  // batch position — ties between equal keys resolve last-wins
};

static bool parse_batch_views(const u8* p, size_t n, std::vector<OpView>& out) {
  if (n < 4) return false;
  u32 count = get_u32(p);
  size_t off = 4;
  out.clear();
  out.reserve(count);
  for (u32 i = 0; i < count; i++) {
    if (off + 5 > n) return false;
    u8 type = p[off];
    off += 1;
    u32 klen = get_u32(p + off);
    off += 4;
    if (klen > n || off + klen + 4 > n) return false;
    std::string_view key((const char*)p + off, klen);
    off += klen;
    u32 vlen = get_u32(p + off);
    off += 4;
    if (vlen > n || off + vlen > n) return false;
    std::string_view val((const char*)p + off, vlen);
    off += vlen;
    out.push_back(OpView{key, val, type == 1, i});
  }
  return off == n;
}

// ---------------------------------------------------------------------------
// Memtable: arena-backed skiplist
// ---------------------------------------------------------------------------

constexpr int SKIP_MAX_HEIGHT = 12;

struct SkipNode {
  std::string_view key, val;
  bool del;
  int height;
  SkipNode* next[1];  // over-allocated to `height`
};

struct Memtable {
  SkipNode* head;
  size_t bytes = 0;
  size_t count = 0;
  u64 wal_segment = 0;  // segment whose records this memtable holds
  std::vector<std::string*> arena;  // owned batch payload copies
  u64 rnd = 0x9E3779B97F4A7C15ull;
  SkipNode* prev[SKIP_MAX_HEIGHT];

  Memtable() {
    head = alloc_node(SKIP_MAX_HEIGHT);
    for (int i = 0; i < SKIP_MAX_HEIGHT; i++) head->next[i] = nullptr;
  }
  ~Memtable() {
    SkipNode* n = head;
    while (n) {
      SkipNode* nx = n->next[0];
      free(n);
      n = nx;
    }
    for (auto* s : arena) delete s;
  }
  Memtable(const Memtable&) = delete;
  Memtable& operator=(const Memtable&) = delete;

  static SkipNode* alloc_node(int h) {
    SkipNode* n = (SkipNode*)malloc(sizeof(SkipNode) +
                                    (size_t)(h - 1) * sizeof(SkipNode*));
    n->height = h;
    return n;
  }

  int random_height() {
    rnd ^= rnd << 13;
    rnd ^= rnd >> 7;
    rnd ^= rnd << 17;
    int h = 1;
    u64 r = rnd;
    while (h < SKIP_MAX_HEIGHT && (r & 3) == 0) {
      h++;
      r >>= 2;
    }
    return h;
  }

  // Fill prev[] with the update path for `key`, starting the search at
  // `start` (head, or the previous insert's path when keys ascend — the
  // sorted-batch splice that makes bulk ingest near-linear).
  void find_path(std::string_view key, SkipNode* start) {
    SkipNode* x = start;
    for (int lvl = SKIP_MAX_HEIGHT - 1; lvl >= 0; lvl--) {
      while (x->next[lvl] && x->next[lvl]->key < key) x = x->next[lvl];
      prev[lvl] = x;
    }
  }

  // prev[] must hold the update path for `key` (find_path). Last-wins.
  void insert_at_path(std::string_view key, std::string_view val, bool del) {
    SkipNode* cur = prev[0]->next[0];
    if (cur && cur->key == key) {
      bytes += val.size() - cur->val.size();
      cur->val = val;
      cur->del = del;
      return;
    }
    int h = random_height();
    SkipNode* n = alloc_node(h);
    n->key = key;
    n->val = val;
    n->del = del;
    for (int i = 0; i < h; i++) {
      n->next[i] = prev[i]->next[i];
      prev[i]->next[i] = n;
    }
    bytes += key.size() + val.size() + sizeof(SkipNode) +
             (size_t)h * sizeof(SkipNode*);
    count++;
  }

  // Ingest one parsed batch: sort the views, then splice in ascending
  // order. `payload_copy` ownership transfers to the arena.
  void ingest(std::string* payload_copy, std::vector<OpView>& ops) {
    arena.push_back(payload_copy);
    std::sort(ops.begin(), ops.end(), [](const OpView& a, const OpView& b) {
      if (a.key != b.key) return a.key < b.key;
      return a.order < b.order;
    });
    SkipNode* start = head;
    std::string_view last_key;
    bool have_last = false;
    for (auto& op : ops) {
      if (have_last && op.key == last_key) {
        // duplicate within the batch: overwrite in place (path still valid)
        insert_at_path(op.key, op.val, op.del);
        continue;
      }
      find_path(op.key, start);
      insert_at_path(op.key, op.val, op.del);
      // every prev[] node keys < op.key <= next keys: resume from the
      // highest-level predecessor instead of head
      start = prev[SKIP_MAX_HEIGHT - 1];
      last_key = op.key;
      have_last = true;
    }
  }

  // 1 found (val/del out), 0 absent
  int find(std::string_view key, std::string_view& val, bool& del) const {
    SkipNode* x = head;
    for (int lvl = SKIP_MAX_HEIGHT - 1; lvl >= 0; lvl--) {
      while (x->next[lvl] && x->next[lvl]->key < key) x = x->next[lvl];
    }
    SkipNode* cur = x->next[0];
    if (cur && cur->key == key) {
      val = cur->val;
      del = cur->del;
      return 1;
    }
    return 0;
  }

  SkipNode* lower_bound(std::string_view key) const {
    SkipNode* x = head;
    for (int lvl = SKIP_MAX_HEIGHT - 1; lvl >= 0; lvl--) {
      while (x->next[lvl] && x->next[lvl]->key < key) x = x->next[lvl];
    }
    return x->next[0];
  }

  SkipNode* first() const { return head->next[0]; }
  bool empty() const { return head->next[0] == nullptr; }
};

// ---------------------------------------------------------------------------
// SSTable v2:
//   "LSS2" | data blocks | bloom filter | index | footer "2SSL"
// data block: entries (u8 type, u32 klen, key, u32 vlen, val)*, ~4 KiB
// index: u32 min_klen, min_key, then per block
//        (u32 last_klen, last_key, u64 off, u32 len, u32 crc)
// footer (44 bytes): u64 filter_off, u64 index_off, u32 filter_len,
//        u32 block_count, u32 bloom_k, u64 entry_count,
//        u32 crc(filter+index), "2SSL"
// ---------------------------------------------------------------------------

constexpr size_t FOOTER_LEN = 44;

struct BlockMeta {
  std::string last_key;
  u64 off;
  u32 len;
  u32 crc;
};

struct Table {
  std::string path;
  int fd = -1;
  u64 id = 0;  // process-unique block-cache namespace
  u64 entry_count = 0;
  u32 bloom_k = BLOOM_K;
  std::string bloom;  // bit array
  std::string min_key, max_key;
  std::vector<BlockMeta> blocks;

  ~Table() {
    if (fd >= 0) ::close(fd);
  }

  bool bloom_may_contain(std::string_view key) const {
    if (bloom.empty()) return true;
    u64 h1 = hash64(key.data(), key.size(), 0x6c736d31);
    u64 h2 = hash64(key.data(), key.size(), 0x6c736d32) | 1;
    u64 nbits = (u64)bloom.size() * 8;
    for (u32 i = 0; i < bloom_k; i++) {
      u64 bit = (h1 + i * h2) % nbits;
      if (!((u8)bloom[bit / 8] & (1u << (bit % 8)))) return false;
    }
    return true;
  }
};

// Streaming SST writer: data blocks coalesced through a write buffer, key
// hashes collected for the bloom filter sized at finish(). The optional
// throttle (compaction rate limiting) runs per flushed buffer OFF the
// engine lock.
struct TableBuilder {
  std::string path, tmp;
  int fd = -1;
  std::string buf;      // pending file bytes
  std::string block;    // current data block
  std::string last_key;
  std::string first_key;
  bool has_first = false;
  u64 file_off = 4;     // past magic
  u64 entries = 0;
  std::vector<BlockMeta> metas;
  std::vector<std::pair<u64, u64>> hashes;
  u64 (*throttle)(void*, u64) = nullptr;  // (ctx, bytes) -> ignored
  void* throttle_ctx = nullptr;

  bool open(const std::string& p) {
    path = p;
    tmp = p + ".tmp";
    fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    buf = "LSS2";
    return true;
  }

  bool spill() {
    if (buf.empty()) return true;
    if (!write_all(fd, buf.data(), buf.size())) return false;
    if (throttle) throttle(throttle_ctx, buf.size());
    buf.clear();
    return true;
  }

  void emit_block() {
    if (block.empty()) return;
    BlockMeta m;
    m.last_key = last_key;
    m.off = file_off;
    m.len = (u32)block.size();
    m.crc = crc32((const u8*)block.data(), block.size());
    metas.push_back(std::move(m));
    file_off += block.size();
    buf += block;
    block.clear();
  }

  bool add(std::string_view key, std::string_view val, bool del) {
    if (!has_first) {
      first_key.assign(key.data(), key.size());
      has_first = true;
    }
    block.push_back(del ? 1 : 0);
    put_u32(block, (u32)key.size());
    block.append(key.data(), key.size());
    put_u32(block, (u32)val.size());
    block.append(val.data(), val.size());
    last_key.assign(key.data(), key.size());
    hashes.emplace_back(hash64(key.data(), key.size(), 0x6c736d31),
                        hash64(key.data(), key.size(), 0x6c736d32) | 1);
    entries++;
    if (block.size() >= BLOCK_TARGET) {
      emit_block();
      if (buf.size() >= WRITE_BUF && !spill()) return false;
    }
    return true;
  }

  void abandon() {
    if (fd >= 0) ::close(fd);
    fd = -1;
    ::unlink(tmp.c_str());
  }

  bool finish() {
    emit_block();
    // bloom filter sized to the final entry count
    std::string filter;
    if (entries) {
      u64 nbits = entries * BLOOM_BITS_PER_KEY;
      filter.assign((nbits + 7) / 8, '\0');
      nbits = (u64)filter.size() * 8;
      for (auto& h : hashes)
        for (u32 i = 0; i < BLOOM_K; i++) {
          u64 bit = (h.first + i * h.second) % nbits;
          filter[bit / 8] = (char)((u8)filter[bit / 8] | (1u << (bit % 8)));
        }
    }
    u64 filter_off = file_off;
    std::string index;
    put_u32(index, (u32)first_key.size());
    index += first_key;
    for (auto& m : metas) {
      put_u32(index, (u32)m.last_key.size());
      index += m.last_key;
      put_u64(index, m.off);
      put_u32(index, m.len);
      put_u32(index, m.crc);
    }
    u64 index_off = filter_off + filter.size();
    std::string tail = filter + index;
    u32 crc = crc32((const u8*)tail.data(), tail.size());
    std::string footer;
    put_u64(footer, filter_off);
    put_u64(footer, index_off);
    put_u32(footer, (u32)filter.size());
    put_u32(footer, (u32)metas.size());
    put_u32(footer, BLOOM_K);
    put_u64(footer, entries);
    put_u32(footer, crc);
    footer += "2SSL";
    buf += tail;
    buf += footer;
    if (!spill() || ::fsync(fd) != 0) {
      abandon();
      return false;
    }
    ::close(fd);
    fd = -1;
    if (::rename(tmp.c_str(), path.c_str()) != 0) {
      ::unlink(tmp.c_str());
      return false;
    }
    return true;
  }
};

static bool load_table_inner(Table& t) {
  t.fd = ::open(t.path.c_str(), O_RDONLY);
  if (t.fd < 0) return false;
  off_t size = ::lseek(t.fd, 0, SEEK_END);
  if (size < (off_t)(4 + FOOTER_LEN)) return false;
  u8 footer[FOOTER_LEN];
  if (::pread(t.fd, footer, FOOTER_LEN, size - FOOTER_LEN) !=
      (ssize_t)FOOTER_LEN)
    return false;
  if (memcmp(footer + FOOTER_LEN - 4, "2SSL", 4) != 0) return false;
  u64 filter_off = get_u64(footer);
  u64 index_off = get_u64(footer + 8);
  u32 filter_len = get_u32(footer + 16);
  u32 block_count = get_u32(footer + 20);
  t.bloom_k = get_u32(footer + 24);
  t.entry_count = get_u64(footer + 28);
  u32 want_crc = get_u32(footer + 36);
  u64 tail_end = (u64)size - FOOTER_LEN;
  if (filter_off > tail_end || index_off < filter_off ||
      index_off > tail_end || index_off - filter_off != filter_len ||
      t.bloom_k == 0 || t.bloom_k > 32)
    return false;
  size_t tail_len = (size_t)(tail_end - filter_off);
  std::vector<u8> tail(tail_len);
  if (tail_len && ::pread(t.fd, tail.data(), tail_len, (off_t)filter_off) !=
                      (ssize_t)tail_len)
    return false;
  if (crc32(tail.data(), tail_len) != want_crc) return false;
  t.bloom.assign((const char*)tail.data(), filter_len);
  const u8* idx = tail.data() + filter_len;
  size_t ilen = tail_len - filter_len;
  size_t off = 0;
  if (off + 4 > ilen) return false;
  u32 minklen = get_u32(idx + off);
  off += 4;
  if (minklen > ilen || off + minklen > ilen) return false;
  t.min_key.assign((const char*)idx + off, minklen);
  off += minklen;
  t.blocks.clear();
  t.blocks.reserve(block_count);
  for (u32 i = 0; i < block_count; i++) {
    if (off + 4 > ilen) return false;
    u32 klen = get_u32(idx + off);
    off += 4;
    if (klen > ilen || off + klen + 16 > ilen) return false;
    BlockMeta m;
    m.last_key.assign((const char*)idx + off, klen);
    off += klen;
    m.off = get_u64(idx + off);
    off += 8;
    m.len = get_u32(idx + off);
    off += 4;
    m.crc = get_u32(idx + off);
    off += 4;
    if (m.off < 4 || m.off + m.len > filter_off) return false;
    t.blocks.push_back(std::move(m));
  }
  if (off != ilen) return false;
  t.max_key = t.blocks.empty() ? t.min_key : t.blocks.back().last_key;
  return true;
}

static bool load_table(Table& t) {
  // on ANY failure the fd must close here: a corrupted store is retried by
  // operators, and a long-lived process probing bad dirs must not leak fds
  if (!load_table_inner(t)) {
    if (t.fd >= 0) ::close(t.fd);
    t.fd = -1;
    return false;
  }
  return true;
}

// entry parse within a loaded block; returns false on structural overrun
struct BlockParse {
  const u8* p = nullptr;
  size_t n = 0, off = 0;
  std::string_view key{}, val{};
  bool del = false;
  bool next() {
    if (off >= n) return false;
    if (off + 9 > n) return false;
    del = p[off] == 1;
    u32 klen = get_u32(p + off + 1);
    size_t o = off + 5;
    if (klen > n || o + klen + 4 > n) return false;
    key = std::string_view((const char*)p + o, klen);
    o += klen;
    u32 vlen = get_u32(p + o);
    o += 4;
    if (vlen > n || o + vlen > n) return false;
    val = std::string_view((const char*)p + o, vlen);
    off = o + vlen;
    return true;
  }
};

// ---------------------------------------------------------------------------
// Shared LRU block cache (point reads only; scans and compaction stream
// past it to avoid pollution)
// ---------------------------------------------------------------------------

struct BlockCache {
  struct Key {
    u64 tid, off;
    bool operator==(const Key& o) const { return tid == o.tid && off == o.off; }
  };
  struct KeyHash {
    size_t operator()(const Key& k) const {
      return (size_t)(k.tid * 0x9E3779B97F4A7C15ull ^ k.off);
    }
  };
  struct Entry {
    std::shared_ptr<std::string> data;
    std::list<Key>::iterator lru_it;
  };
  size_t cap = 32u << 20;
  size_t size = 0;
  std::unordered_map<Key, Entry, KeyHash> map;
  std::list<Key> lru;  // front = most recent

  std::shared_ptr<std::string> get(u64 tid, u64 off) {
    auto it = map.find(Key{tid, off});
    if (it == map.end()) return nullptr;
    lru.splice(lru.begin(), lru, it->second.lru_it);
    return it->second.data;
  }

  void put(u64 tid, u64 off, std::shared_ptr<std::string> data) {
    Key k{tid, off};
    if (map.count(k)) return;
    lru.push_front(k);
    size += data->size();
    map.emplace(k, Entry{std::move(data), lru.begin()});
    while (size > cap && !lru.empty()) {
      Key victim = lru.back();
      auto vit = map.find(victim);
      size -= vit->second.data->size();
      map.erase(vit);
      lru.pop_back();
    }
  }

  void drop_table(u64 tid) {
    for (auto it = map.begin(); it != map.end();) {
      if (it->first.tid == tid) {
        size -= it->second.data->size();
        lru.erase(it->second.lru_it);
        it = map.erase(it);
      } else {
        ++it;
      }
    }
  }
};

// Streaming cursor over one table (scan/compaction path, no cache)
struct TableCursor {
  const Table* t = nullptr;
  size_t bi = 0;
  std::string block;
  BlockParse bp{nullptr, 0};
  bool valid = false;
  bool io_error = false;

  bool load_block(size_t i) {
    if (i >= t->blocks.size()) {
      valid = false;
      return false;
    }
    const BlockMeta& m = t->blocks[i];
    block.resize(m.len);
    if (m.len && ::pread(t->fd, &block[0], m.len, (off_t)m.off) !=
                     (ssize_t)m.len) {
      io_error = true;
      valid = false;
      return false;
    }
    if (crc32((const u8*)block.data(), block.size()) != m.crc) {
      io_error = true;
      valid = false;
      return false;
    }
    bi = i;
    bp = BlockParse{(const u8*)block.data(), block.size()};
    return true;
  }

  void start(const Table* table) {
    t = table;
    valid = false;
    io_error = false;
    if (t->blocks.empty()) return;
    if (load_block(0)) step();
  }

  void seek(const Table* table, std::string_view key) {
    t = table;
    valid = false;
    io_error = false;
    // first block whose last_key >= key
    size_t lo = 0, hi = t->blocks.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (std::string_view(t->blocks[mid].last_key) < key)
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo >= t->blocks.size()) return;
    if (!load_block(lo)) return;
    step();
    while (valid && bp.key < key) {
      // advance within the block; BlockParse::key points into `block`
      step();
    }
    // cursor fields (key/val) are bp's views
  }

  void step() {
    if (bp.next()) {
      valid = true;
      return;
    }
    if (bp.off != bp.n) {  // structural damage inside the block
      io_error = true;
      valid = false;
      return;
    }
    if (bi + 1 < t->blocks.size()) {
      if (load_block(bi + 1)) step();
      return;
    }
    valid = false;
  }

  std::string_view key() const { return bp.key; }
  std::string_view val() const { return bp.val; }
  bool del() const { return bp.del; }
};

// ---------------------------------------------------------------------------
// Flight-recorder trace ring (shared 32-byte big-endian record layout with
// consensus/native/consensus_rt.cpp and utils/tracing.py). Unlike the
// consensus engine this store is multi-threaded, so the ring takes its own
// leaf mutex and every record carries the emitting thread's role as its tid
// — the merge layer renders those as named threads (wal writer / flusher /
// compactor) in the Chrome trace. Timestamps are raw CLOCK_MONOTONIC ns;
// lsm_monotonic_ns anchors the Python clock-offset handshake.
// ---------------------------------------------------------------------------

static inline u64 trace_now_ns() {
  return (u64)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

enum LsmTraceKind : u32 {
  LK_WAL_ENQ = 20,    // span: record encode (crc+frame); a=payload bytes
  LK_WAL_FSYNC = 21,  // span: write+fsync; a=group-commit records, b=bytes
  LK_SEAL = 22,       // instant: memtable sealed; a=bytes, b=new segment
  LK_FLUSH = 23,      // span: memtable -> SST; a=bytes, b=sst seq
  LK_COMPACT = 24,    // span: full merge; a=input tables, b=output seq
  LK_WAIT = 25,       // span: caller blocked; a=wait resource (4=fsync)
};

enum LsmTraceTid : u32 {
  LT_CALLER = 0,  // API caller thread (write/seal path)
  LT_WAL = 1,
  LT_FLUSHER = 2,
  LT_COMPACTOR = 3,
};

struct TraceEvent {
  u64 ts_ns, dur_ns;
  u32 kind, tid, a, b;
};

struct TraceRing {
  std::mutex mu;  // leaf lock: push/drain only, never acquires another
  std::vector<TraceEvent> buf;
  size_t cap = 16384;
  size_t w = 0, count = 0;
  u64 dropped = 0;
  std::atomic<bool> enabled{true};

  void configure(size_t capacity) {
    std::lock_guard<std::mutex> g(mu);
    buf.clear();
    w = count = 0;
    cap = capacity;
    enabled.store(capacity > 0, std::memory_order_relaxed);
  }
  void push(u64 ts, u64 dur, u32 kind, u32 tid, u32 a, u32 b) {
    if (!enabled.load(std::memory_order_relaxed)) return;
    std::lock_guard<std::mutex> g(mu);
    if (!cap) return;
    if (buf.size() != cap) buf.resize(cap);
    buf[w] = {ts, dur, kind, tid, a, b};
    w = (w + 1) % cap;
    if (count < cap)
      count++;
    else
      dropped++;  // overwrote the oldest unread record
  }
};

static inline void trace_put32(std::string& out, u32 v) {
  char b[4] = {(char)(v >> 24), (char)(v >> 16), (char)(v >> 8), (char)v};
  out.append(b, 4);
}

static inline void trace_put64(std::string& out, u64 v) {
  trace_put32(out, (u32)(v >> 32));
  trace_put32(out, (u32)v);
}

static inline u32 trace_clamp32(u64 v) {
  return v > 0xFFFFFFFFull ? 0xFFFFFFFFu : (u32)v;
}

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

struct Stats {
  u64 bloom_neg = 0;    // filter ruled a table out (saved a block fetch)
  u64 bloom_pass = 0;   // filter passed; block consulted
  u64 cache_hit = 0;
  u64 cache_miss = 0;
  u64 wal_fsyncs = 0;
  u64 wal_records = 0;
  u64 compactions = 0;
};

struct Lsm {
  std::string dir;
  size_t flush_threshold = 32u << 20;  // active-memtable seal point
  size_t compact_tables = 6;           // full-compact beyond this many
  u64 compact_rate_mbps = 0;           // 0 = unthrottled
  u64 next_seq = 1;                    // SST file sequence
  u64 next_segment = 1;                // WAL segment id
  u64 oldest_segment = 1;              // lowest segment possibly on disk
  u64 next_table_id = 1;               // block-cache namespace

  // db state (memtables, tables, manifest) — guarded by mu/db_cv
  std::mutex mu;
  std::condition_variable db_cv;
  std::unique_ptr<Memtable> mem;
  std::deque<std::unique_ptr<Memtable>> imm;  // oldest..newest, sealed
  std::vector<std::unique_ptr<Table>> tables;  // oldest..newest
  BlockCache cache;
  Stats stats;
  TraceRing trace;  // flight recorder (own leaf mutex, see TraceRing)
  bool io_failed = false;  // a background flush failed: fail fast, loudly

  // WAL writer — guarded by wal_mu
  std::mutex wal_mu;
  std::condition_variable wal_work, wal_done;
  std::string wal_pending;
  u64 wal_enqueued = 0, wal_durable = 0;
  int wal_fd = -1;
  bool wal_stop = false, wal_error = false;
  std::thread wal_thr;

  // flusher / compactor control — guarded by bg_mu
  std::mutex bg_mu;
  std::condition_variable bg_cv;
  bool flush_stop = false;
  std::thread flush_thr;
  bool compact_requested = false, compact_running = false,
       compact_stop = false;
  std::thread compact_thr;

  std::string manifest_path() const { return dir + "/MANIFEST"; }
  std::string table_path(u64 seq) const {
    char buf[40];
    snprintf(buf, sizeof buf, "/sst_%012llu.dat", (unsigned long long)seq);
    return dir + buf;
  }
  std::string segment_path(u64 id) const {
    char buf[32];
    snprintf(buf, sizeof buf, "/wal_%06llu.log", (unsigned long long)id);
    return dir + buf;
  }

  // ---- manifest ------------------------------------------------------------

  bool write_manifest_locked() {
    std::string body;
    for (auto& t : tables) {
      size_t slash = t->path.rfind('/');
      body += t->path.substr(slash + 1);
      body.push_back('\n');
    }
    std::string tmp = manifest_path() + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    if (!write_all(fd, body.data(), body.size()) || ::fsync(fd) != 0) {
      ::close(fd);
      return false;
    }
    ::close(fd);
    if (::rename(tmp.c_str(), manifest_path().c_str()) != 0) return false;
    return fsync_path(dir);
  }

  // ---- open / recovery -----------------------------------------------------

  bool open_dirs() {
    crc_init();
    ::mkdir(dir.c_str(), 0755);
    // a v1-era store (single wal.log + "LSST" tables) predates the segment
    // format: refuse loudly rather than silently ignoring its WAL
    struct stat st;
    if (::stat((dir + "/wal.log").c_str(), &st) == 0 && st.st_size > 0)
      return false;
    // manifest -> tables
    tables.clear();
    FILE* mf = fopen(manifest_path().c_str(), "r");
    std::vector<std::string> manifest_names;
    if (mf) {
      char line[256];
      while (fgets(line, sizeof line, mf)) {
        size_t n = strlen(line);
        while (n && (line[n - 1] == '\n' || line[n - 1] == '\r')) line[--n] = 0;
        if (!n) continue;
        manifest_names.push_back(line);
        auto t = std::make_unique<Table>();
        t->path = dir + "/" + line;
        t->id = next_table_id++;
        if (!load_table(*t)) {
          fclose(mf);
          tables.clear();
          return false;
        }
        unsigned long long seq = 0;
        sscanf(line, "sst_%012llu.dat", &seq);
        if (seq >= next_seq) next_seq = seq + 1;
        tables.push_back(std::move(t));
      }
      fclose(mf);
    }
    // directory sweep: orphan SSTs (flush/compaction output whose manifest
    // swap never landed — their data is still WAL- or manifest-reachable),
    // stale .tmp files, and the WAL segment inventory
    std::vector<u64> segments;
    DIR* d = opendir(dir.c_str());
    if (!d) {
      tables.clear();
      return false;
    }
    while (dirent* e = readdir(d)) {
      std::string name = e->d_name;
      unsigned long long num = 0;
      if (name.size() > 4 &&
          name.compare(name.size() - 4, 4, ".tmp") == 0) {
        ::unlink((dir + "/" + name).c_str());
      } else if (sscanf(name.c_str(), "sst_%012llu.dat", &num) == 1) {
        if (num >= next_seq) next_seq = num + 1;
        bool in_manifest = false;
        for (auto& m : manifest_names)
          if (m == name) {
            in_manifest = true;
            break;
          }
        if (!in_manifest) ::unlink((dir + "/" + name).c_str());
      } else if (sscanf(name.c_str(), "wal_%06llu.log", &num) == 1) {
        segments.push_back(num);
      }
    }
    closedir(d);
    std::sort(segments.begin(), segments.end());

    // WAL replay, oldest segment first. Only the LAST (active) segment may
    // carry a torn tail — it is discarded AND truncated on disk (garbage
    // ahead of future appends would strand every later record). A bad
    // record in an earlier, sealed segment is corruption: refuse.
    mem = std::make_unique<Memtable>();
    for (size_t si = 0; si < segments.size(); si++) {
      bool is_last = si + 1 == segments.size();
      std::string path = segment_path(segments[si]);
      int rfd = ::open(path.c_str(), O_RDONLY);
      if (rfd < 0) {
        tables.clear();
        return false;
      }
      off_t size = ::lseek(rfd, 0, SEEK_END);
      std::vector<u8> buf((size_t)size);
      if (size > 0 &&
          ::pread(rfd, buf.data(), (size_t)size, 0) != (ssize_t)size) {
        ::close(rfd);
        tables.clear();
        return false;
      }
      ::close(rfd);
      size_t off = 0;
      while (off + 8 <= buf.size()) {
        u32 crc = get_u32(buf.data() + off);
        u32 len = get_u32(buf.data() + off + 4);
        if (len > buf.size() || off + 8 + len > buf.size()) break;
        if (crc32(buf.data() + off + 8, len) != crc) break;
        auto* copy = new std::string((const char*)buf.data() + off + 8, len);
        std::vector<OpView> ops;
        if (!parse_batch_views((const u8*)copy->data(), copy->size(), ops)) {
          delete copy;
          break;
        }
        mem->ingest(copy, ops);
        off += 8 + len;
      }
      if (off < buf.size()) {
        if (!is_last) {
          tables.clear();
          return false;
        }
        int tfd = ::open(path.c_str(), O_WRONLY);
        bool ok = tfd >= 0 && ::ftruncate(tfd, (off_t)off) == 0 &&
                  ::fsync(tfd) == 0;
        if (tfd >= 0) ::close(tfd);
        if (!ok) {
          tables.clear();
          return false;
        }
      }
    }
    u64 active = segments.empty() ? 1 : segments.back();
    next_segment = active + 1;
    oldest_segment = segments.empty() ? 1 : segments.front();
    mem->wal_segment = active;
    wal_fd = ::open(segment_path(active).c_str(),
                    O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (wal_fd < 0) {
      tables.clear();
      return false;
    }
    // workers only start once recovery is committed
    wal_thr = std::thread([this] { wal_loop(); });
    flush_thr = std::thread([this] { flush_loop(); });
    compact_thr = std::thread([this] { compact_loop(); });
    // a replayed memtable over the seal point flushes like any other
    std::unique_lock<std::mutex> lk(mu);
    if (mem->bytes >= flush_threshold) seal_memtable(lk);
    return true;
  }

  // ---- WAL writer ----------------------------------------------------------

  void wal_loop() {
    std::unique_lock<std::mutex> lk(wal_mu);
    for (;;) {
      wal_work.wait(lk, [&] { return wal_stop || !wal_pending.empty(); });
      if (wal_pending.empty() && wal_stop) break;
      std::string buf;
      buf.swap(wal_pending);
      u64 through = wal_enqueued;
      u64 batch = through - wal_durable;  // group-commit size (records)
      int fd = wal_fd;
      lk.unlock();
      u64 t0 = trace_now_ns();
      bool ok = write_all(fd, buf.data(), buf.size()) && ::fsync(fd) == 0;
      if (ok)
        trace.push(t0, trace_now_ns() - t0, LK_WAL_FSYNC, LT_WAL,
                   trace_clamp32(batch), trace_clamp32(buf.size()));
      lk.lock();
      if (!ok) {
        wal_error = true;
      } else {
        wal_durable = through;
        stats_wal_fsyncs++;
      }
      wal_done.notify_all();
    }
  }
  u64 stats_wal_fsyncs = 0;  // wal_mu

  // caller holds mu (ordering: mu -> wal_mu). Returns the record's seq.
  u64 wal_enqueue_locked(const u8* payload, size_t len) {
    u64 t0 = trace_now_ns();
    std::string rec;
    rec.reserve(len + 8);
    put_u32(rec, crc32(payload, len));
    put_u32(rec, (u32)len);
    rec.append((const char*)payload, len);
    trace.push(t0, trace_now_ns() - t0, LK_WAL_ENQ, LT_CALLER,
               trace_clamp32(len), 0);
    std::lock_guard<std::mutex> g(wal_mu);
    wal_pending += rec;
    u64 seq = ++wal_enqueued;
    wal_work.notify_one();
    return seq;
  }

  // block until `seq` is durable (or the writer failed). No locks held on
  // entry — this is the post-apply ack wait.
  bool wal_wait(u64 seq) {
    std::unique_lock<std::mutex> lk(wal_mu);
    if (wal_error || wal_durable >= seq) return !wal_error;
    // the caller genuinely blocks on durability: record the wait so the
    // era report can attribute it to the fsync bucket
    bool timed = trace.enabled.load(std::memory_order_relaxed);
    u64 t0 = timed ? trace_now_ns() : 0;
    wal_done.wait(lk, [&] { return wal_error || wal_durable >= seq; });
    if (timed)
      trace.push(t0, trace_now_ns() - t0, LK_WAIT, LT_CALLER, 4, 0);
    return !wal_error;
  }

  // drain the writer completely (rotation/flush/debug). Caller holds mu.
  bool wal_drain_locked() {
    std::unique_lock<std::mutex> lk(wal_mu);
    wal_done.wait(lk, [&] {
      return wal_error || (wal_pending.empty() && wal_durable == wal_enqueued);
    });
    return !wal_error;
  }

  // ---- write path ----------------------------------------------------------

  // Seal the active memtable into the immutable queue and rotate the WAL
  // to a fresh segment. Caller holds mu (as unique_lock, for backpressure).
  bool seal_memtable(std::unique_lock<std::mutex>& lk) {
    if (mem->empty()) return true;
    // every record of this memtable must be on disk before the segment is
    // considered sealed (a sealed segment is never torn)
    if (!wal_drain_locked()) return false;
    u64 seg = next_segment++;
    int nfd = ::open(segment_path(seg).c_str(),
                     O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (nfd < 0) return false;
    {
      std::lock_guard<std::mutex> g(wal_mu);
      ::close(wal_fd);
      wal_fd = nfd;
    }
    trace.push(trace_now_ns(), 0, LK_SEAL, LT_CALLER,
               trace_clamp32(mem->bytes), trace_clamp32(seg));
    imm.push_back(std::move(mem));
    mem = std::make_unique<Memtable>();
    mem->wal_segment = seg;
    db_cv.notify_all();  // the flusher waits on db_cv
    // backpressure: a writer outrunning the flusher stalls here instead of
    // queueing unbounded sealed memtables
    db_cv.wait(lk, [&] {
      return imm.size() < IMM_QUEUE_STALL || io_failed || flush_stop;
    });
    return !io_failed;
  }

  int write_batch(const u8* payload, size_t len) {
    auto* copy = new std::string((const char*)payload, len);
    std::vector<OpView> ops;
    if (!parse_batch_views((const u8*)copy->data(), copy->size(), ops)) {
      delete copy;
      return -1;
    }
    u64 seq;
    {
      std::unique_lock<std::mutex> lk(mu);
      if (io_failed) {
        delete copy;
        return -1;
      }
      // enqueue first: the writer thread overlaps the write()+fsync() with
      // the memtable splice below
      seq = wal_enqueue_locked(payload, len);
      {
        std::lock_guard<std::mutex> g(wal_mu);
        stats.wal_records++;
      }
      mem->ingest(copy, ops);
      if (mem->bytes >= flush_threshold) {
        if (!seal_memtable(lk)) return -1;
      }
    }
    // ack strictly after the WAL fsync (persist-before-ack)
    return wal_wait(seq) ? 0 : -1;
  }

  // write_batch minus the durability wait: enqueue onto the writer thread,
  // splice the memtable, return the WAL seq as an async ticket. The caller
  // overlaps its next work (more trie hashing, the next batch's encode)
  // with this record's write()+fsync(), then collects durability via
  // write_barrier before acking anything that references the batch. The
  // WAL is append-ordered, so a later record's fsync implies this one's.
  // Returns 0 on failure (seqs start at 1).
  u64 write_batch_async(const u8* payload, size_t len) {
    auto* copy = new std::string((const char*)payload, len);
    std::vector<OpView> ops;
    if (!parse_batch_views((const u8*)copy->data(), copy->size(), ops)) {
      delete copy;
      return 0;
    }
    u64 seq;
    {
      std::unique_lock<std::mutex> lk(mu);
      if (io_failed) {
        delete copy;
        return 0;
      }
      seq = wal_enqueue_locked(payload, len);
      {
        std::lock_guard<std::mutex> g(wal_mu);
        stats.wal_records++;
      }
      mem->ingest(copy, ops);
      if (mem->bytes >= flush_threshold) {
        if (!seal_memtable(lk)) return 0;
      }
    }
    return seq;
  }

  int write_barrier(u64 seq) { return wal_wait(seq) ? 0 : -1; }

  // Debug crash surface: run the write pipeline only up to `stage`, never
  // applying the memtable — the torn windows the crash matrix needs.
  //   stage 0 ("encoded, not fsynced"): a PREFIX of the record reaches the
  //     segment (last byte dropped, no fsync) — the torn-tail image an
  //     unflushed page cache can leave; replay must discard+truncate it.
  //   stage 1 ("fsynced, not applied/acked"): the full record is durable
  //     but the caller never got its ack; replay must apply it (the
  //     contract is acked => durable, not the converse).
  // Deterministic in BOTH harness modes (in-process raise and SIGKILL):
  // the bytes on disk are identical either way. The engine must be closed
  // afterwards (its memtable no longer matches the replay state).
  int write_batch_partial(const u8* payload, size_t len, int stage) {
    std::vector<OpView> ops;
    if (!parse_batch_views(payload, len, ops)) return -1;
    std::unique_lock<std::mutex> lk(mu);
    if (!wal_drain_locked()) return -1;
    std::string rec;
    put_u32(rec, crc32(payload, len));
    put_u32(rec, (u32)len);
    rec.append((const char*)payload, len);
    if (stage == 0 && !rec.empty()) rec.pop_back();  // torn tail
    std::lock_guard<std::mutex> g(wal_mu);  // writer idle: fd is ours
    if (!write_all(wal_fd, rec.data(), rec.size())) return -1;
    if (stage >= 1 && ::fsync(wal_fd) != 0) return -1;
    return 0;
  }

  // ---- flusher -------------------------------------------------------------

  void flush_loop() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      db_cv.wait(lk, [&] { return flush_stop || !imm.empty(); });
      if (flush_stop) break;
      Memtable* m = imm.front().get();  // stays visible to readers
      u64 seq = next_seq++;
      u64 tid = next_table_id++;
      // tombstones must persist unless this becomes the ONLY table
      bool only = tables.empty();
      lk.unlock();
      // the sealed memtable is immutable: stream it without the lock
      u64 t0 = trace_now_ns();
      auto table = flush_memtable_to_sst(m, seq, tid, only);
      if (table)
        trace.push(t0, trace_now_ns() - t0, LK_FLUSH, LT_FLUSHER,
                   trace_clamp32(m->bytes), trace_clamp32(seq));
      lk.lock();
      if (!table) {
        // an unflushable memtable is a hard fault: writers fail fast
        // rather than silently queueing data that can never become tables
        io_failed = true;
        db_cv.notify_all();
        continue;
      }
      tables.push_back(std::move(table));
      if (!write_manifest_locked()) {
        io_failed = true;
        db_cv.notify_all();
        continue;
      }
      u64 seg = m->wal_segment;
      imm.pop_front();
      db_cv.notify_all();  // backpressure waiters + lsm_flush
      maybe_schedule_compaction_locked();
      lk.unlock();
      // every batch in segments <= seg is now SST+manifest-durable
      for (u64 s = oldest_segment; s <= seg; s++)
        ::unlink(segment_path(s).c_str());
      oldest_segment = seg + 1;  // only this thread advances it
      lk.lock();
    }
  }

  std::unique_ptr<Table> flush_memtable_to_sst(Memtable* m, u64 seq, u64 tid,
                                               bool drop_tombstones) {
    TableBuilder b;
    if (!b.open(table_path(seq))) return nullptr;
    for (SkipNode* n = m->first(); n; n = n->next[0]) {
      if (n->del && drop_tombstones) continue;
      if (!b.add(n->key, n->val, n->del)) {
        b.abandon();
        return nullptr;
      }
    }
    if (!b.finish()) return nullptr;
    auto t = std::make_unique<Table>();
    t->path = table_path(seq);
    t->id = tid;
    if (!load_table(*t)) return nullptr;
    return t;
  }

  // ---- compaction ----------------------------------------------------------

  void maybe_schedule_compaction_locked() {
    if (tables.size() > compact_tables) {
      std::lock_guard<std::mutex> g(bg_mu);
      compact_requested = true;
      bg_cv.notify_all();
    }
  }

  void compact_loop() {
    std::unique_lock<std::mutex> lk(bg_mu);
    for (;;) {
      // only one compaction at a time anywhere — the swap logic assumes
      // the first n_in tables are still exactly its inputs
      bg_cv.wait(lk, [&] {
        return compact_stop || (compact_requested && !compact_running);
      });
      if (compact_stop) break;
      compact_requested = false;
      compact_running = true;
      lk.unlock();
      compact_once(/*swap=*/true);
      lk.lock();
      compact_running = false;
      bg_cv.notify_all();
    }
  }

  // serialize a manual (CLI/debug) compaction against the background one
  bool begin_manual_compaction() {
    std::unique_lock<std::mutex> lk(bg_mu);
    bg_cv.wait(lk, [&] {
      return compact_stop || (!compact_running && !compact_requested);
    });
    if (compact_stop) return false;
    compact_running = true;
    return true;
  }
  void end_manual_compaction() {
    std::lock_guard<std::mutex> g(bg_mu);
    compact_running = false;
    bg_cv.notify_all();
  }

  struct Throttle {
    u64 rate_mbps;
    std::chrono::steady_clock::time_point start;
    u64 written = 0;
    static u64 hook(void* ctx, u64 bytes) {
      auto* t = (Throttle*)ctx;
      t->written += bytes;
      if (!t->rate_mbps) return 0;
      double budget_s = (double)t->written / (t->rate_mbps * 1048576.0);
      double spent_s = std::chrono::duration<double>(
                           std::chrono::steady_clock::now() - t->start)
                           .count();
      if (budget_s > spent_s)
        std::this_thread::sleep_for(
            std::chrono::duration<double>(budget_s - spent_s));
      return 0;
    }
  };

  // Full merge of the table set present at entry, newest wins, tombstones
  // drop (the inputs include the oldest table, so nothing below can
  // resurrect). With swap=false (lsm_compact_partial) the merged output is
  // written and renamed but the manifest swap is SKIPPED — the on-disk
  // image a mid-compaction kill -9 leaves, which open() must absorb.
  bool compact_once(bool swap) {
    std::vector<const Table*> inputs;
    size_t n_in;
    u64 seq, tid;
    {
      std::lock_guard<std::mutex> g(mu);
      if (tables.size() < 2 && swap) return true;
      if (tables.empty()) return false;
      n_in = tables.size();
      for (auto& t : tables) inputs.push_back(t.get());
      seq = next_seq++;
      tid = next_table_id++;
    }
    u64 trace_t0 = trace_now_ns();
    Throttle th{compact_rate_mbps, std::chrono::steady_clock::now()};
    TableBuilder b;
    if (!b.open(table_path(seq))) return false;
    b.throttle = Throttle::hook;
    b.throttle_ctx = &th;
    std::vector<TableCursor> cur(n_in);
    for (size_t i = 0; i < n_in; i++) cur[i].start(inputs[i]);
    for (;;) {
      {
        std::lock_guard<std::mutex> g(bg_mu);
        if (compact_stop) {  // engine closing: abandon, WAL/manifest intact
          b.abandon();
          return false;
        }
      }
      // pick the smallest live key; among equals the newest table wins
      int best = -1;
      for (size_t i = 0; i < n_in; i++) {
        if (cur[i].io_error) {
          b.abandon();
          return false;
        }
        if (!cur[i].valid) continue;
        if (best < 0 || cur[i].key() < cur[best].key() ||
            cur[i].key() == cur[best].key())
          best = (int)i;  // later index = newer table
      }
      if (best < 0) break;
      std::string key(cur[best].key());
      if (!cur[best].del()) {
        if (!b.add(key, cur[best].val(), false)) {
          b.abandon();
          return false;
        }
      }  // tombstone: drop (full merge)
      for (size_t i = 0; i < n_in; i++)
        while (cur[i].valid && cur[i].key() == key) cur[i].step();
    }
    if (!b.finish()) return false;
    if (!swap) {  // debug: orphan output left for open() to eat
      trace.push(trace_t0, trace_now_ns() - trace_t0, LK_COMPACT,
                 LT_COMPACTOR, (u32)n_in, trace_clamp32(seq));
      return true;
    }
    auto t = std::make_unique<Table>();
    t->path = table_path(seq);
    t->id = tid;
    if (!load_table(*t)) return false;
    {
      std::lock_guard<std::mutex> g(mu);
      // only compaction removes tables and only one runs: the first n_in
      // entries are exactly our inputs; tables flushed meanwhile stay newer
      std::vector<std::unique_ptr<Table>> next;
      next.push_back(std::move(t));
      for (size_t i = n_in; i < tables.size(); i++)
        next.push_back(std::move(tables[i]));
      std::vector<std::unique_ptr<Table>> old;
      for (size_t i = 0; i < n_in; i++) old.push_back(std::move(tables[i]));
      tables.swap(next);
      if (!write_manifest_locked()) {
        io_failed = true;
        return false;
      }
      for (auto& o : old) {
        cache.drop_table(o->id);
        std::string path = o->path;
        o.reset();  // closes fd
        ::unlink(path.c_str());
      }
      stats.compactions++;
    }
    trace.push(trace_t0, trace_now_ns() - trace_t0, LK_COMPACT, LT_COMPACTOR,
               (u32)n_in, trace_clamp32(seq));
    return true;
  }

  bool wait_compaction() {
    std::unique_lock<std::mutex> lk(bg_mu);
    bg_cv.wait(lk, [&] {
      return (!compact_requested && !compact_running) || compact_stop;
    });
    return true;
  }

  // ---- read path -----------------------------------------------------------

  // 1 found, 0 missing, -1 I/O error (a failed/corrupt block read must NOT
  // read as "key absent" — the state layer would proceed on wrong state)
  int table_find_locked(Table& t, std::string_view key, std::string& out,
                        bool& del) {
    if (t.blocks.empty()) return 0;
    if (key < std::string_view(t.min_key) ||
        std::string_view(t.max_key) < key)
      return 0;
    if (!t.bloom_may_contain(key)) {
      stats.bloom_neg++;
      return 0;
    }
    stats.bloom_pass++;
    size_t lo = 0, hi = t.blocks.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (std::string_view(t.blocks[mid].last_key) < key)
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo >= t.blocks.size()) return 0;
    const BlockMeta& m = t.blocks[lo];
    std::shared_ptr<std::string> block = cache.get(t.id, m.off);
    if (block) {
      stats.cache_hit++;
    } else {
      stats.cache_miss++;
      auto fresh = std::make_shared<std::string>();
      fresh->resize(m.len);
      if (m.len && ::pread(t.fd, &(*fresh)[0], m.len, (off_t)m.off) !=
                       (ssize_t)m.len)
        return -1;
      if (crc32((const u8*)fresh->data(), fresh->size()) != m.crc) return -1;
      cache.put(t.id, m.off, fresh);
      block = std::move(fresh);
    }
    BlockParse bp{(const u8*)block->data(), block->size()};
    while (bp.next()) {
      if (bp.key == key) {
        del = bp.del;
        out.assign(bp.val.data(), bp.val.size());
        return 1;
      }
      if (bp.key > key) return 0;
    }
    if (bp.off != bp.n) return -1;  // structural damage mid-block
    return 0;
  }

  int get(std::string_view key, std::string& out) {
    std::lock_guard<std::mutex> g(mu);
    std::string_view val;
    bool del;
    if (mem->find(key, val, del)) {
      if (del) return 0;
      out.assign(val.data(), val.size());
      return 1;
    }
    for (auto it = imm.rbegin(); it != imm.rend(); ++it) {
      if ((*it)->find(key, val, del)) {
        if (del) return 0;
        out.assign(val.data(), val.size());
        return 1;
      }
    }
    for (auto t = tables.rbegin(); t != tables.rend(); ++t) {
      bool tdel = false;
      int r = table_find_locked(**t, key, out, tdel);
      if (r < 0) return -1;
      if (r == 1) return tdel ? 0 : 1;
    }
    return 0;
  }

  bool scan_prefix(std::string_view prefix, std::string& out) {
    std::lock_guard<std::mutex> g(mu);
    std::map<std::string, std::pair<bool, std::string>, std::less<>> found;
    for (auto& t : tables) {  // oldest -> newest: later overwrites earlier
      TableCursor c;
      c.seek(t.get(), prefix);
      while (c.valid &&
             c.key().substr(0, prefix.size()) == prefix) {
        found[std::string(c.key())] = {c.del(), std::string(c.val())};
        c.step();
      }
      if (c.io_error) return false;
    }
    auto overlay = [&](const Memtable& m) {
      for (SkipNode* n = m.lower_bound(prefix); n; n = n->next[0]) {
        if (n->key.substr(0, prefix.size()) != prefix) break;
        found[std::string(n->key)] = {n->del, std::string(n->val)};
      }
    };
    for (auto& m : imm) overlay(*m);
    overlay(*mem);
    out.clear();
    u32 count = 0;
    std::string body;
    for (auto& kv : found) {
      if (kv.second.first) continue;  // tombstone
      put_u32(body, (u32)kv.first.size());
      body += kv.first;
      put_u32(body, (u32)kv.second.second.size());
      body += kv.second.second;
      count++;
    }
    put_u32(out, count);
    out += body;
    return true;
  }

  // Bounded cursor page: the first `limit` LIVE rows under `prefix` whose
  // key is strictly greater than `start` (exclusive=false makes `start`
  // itself eligible — the "from the front" page). K-way merge over seeked
  // SSTable cursors and memtable skiplist iterators, newest level winning
  // key ties, tombstones consuming their key. A fast-sync snapshot page
  // costs O(seek + page), not the O(keyspace) materialization scan_prefix
  // pays.
  bool scan_from(std::string_view prefix, std::string_view start,
                 bool exclusive, u64 limit, std::string& out) {
    std::lock_guard<std::mutex> g(mu);
    size_t n_tab = tables.size();
    std::vector<TableCursor> tc(n_tab);
    for (size_t i = 0; i < n_tab; i++) {
      tc[i].seek(tables[i].get(), start);
      if (tc[i].io_error) return false;
    }
    // oldest -> newest so the LAST holder of a key in this list is the
    // freshest version: imm is a seal queue (front = oldest), mem newest
    std::vector<SkipNode*> mc;
    for (auto& m : imm) mc.push_back(m->lower_bound(start));
    mc.push_back(mem->lower_bound(start));
    out.clear();
    u32 count = 0;
    std::string body, key;
    while (count < limit) {
      bool any = false;
      std::string_view min_key;
      for (auto& c : tc)
        if (c.valid && (!any || c.key() < min_key)) {
          min_key = c.key();
          any = true;
        }
      for (auto* n : mc)
        if (n && (!any || n->key < min_key)) {
          min_key = n->key;
          any = true;
        }
      if (!any || min_key.substr(0, prefix.size()) != prefix) break;
      key.assign(min_key.data(), min_key.size());
      bool del = false;
      std::string_view val;
      for (auto& c : tc)
        if (c.valid && c.key() == std::string_view(key)) {
          del = c.del();
          val = c.val();
        }
      for (auto* n : mc)
        if (n && n->key == std::string_view(key)) {
          del = n->del;
          val = n->val;
        }
      if (!del && !(exclusive && std::string_view(key) == start)) {
        put_u32(body, (u32)key.size());
        body += key;
        put_u32(body, (u32)val.size());
        body.append(val.data(), val.size());
        count++;
      }
      // advance every holder past this key (views into cursor blocks die
      // here, which is why `key` was copied and `val` already appended)
      for (auto& c : tc) {
        while (c.valid && c.key() == std::string_view(key)) {
          c.step();
          if (c.io_error) return false;
        }
      }
      for (auto*& n : mc)
        while (n && n->key == std::string_view(key)) n = n->next[0];
    }
    put_u32(out, count);
    out += body;
    return true;
  }

  // ---- flush / shutdown ----------------------------------------------------

  // Explicit flush: seal the active memtable and wait until every sealed
  // memtable is a table (tests + clean handover points).
  int flush() {
    std::unique_lock<std::mutex> lk(mu);
    if (io_failed) return -1;
    if (!mem->empty() && !seal_memtable(lk)) return -1;
    db_cv.wait(lk, [&] { return imm.empty() || io_failed || flush_stop; });
    return io_failed ? -1 : 0;
  }

  void close_all() {
    // stop order: WAL writer first (drains pending, so every acked record
    // is durable), then flusher/compactor (whatever they didn't finish is
    // re-coverable from WAL + manifest on the next open)
    {
      std::lock_guard<std::mutex> g(wal_mu);
      wal_stop = true;
      wal_work.notify_all();
    }
    if (wal_thr.joinable()) wal_thr.join();
    {
      std::lock_guard<std::mutex> g(mu);
      flush_stop = true;
      db_cv.notify_all();
    }
    {
      std::lock_guard<std::mutex> g(bg_mu);
      compact_stop = true;
      bg_cv.notify_all();
    }
    if (flush_thr.joinable()) flush_thr.join();
    if (compact_thr.joinable()) compact_thr.join();
    std::lock_guard<std::mutex> g(mu);
    if (wal_fd >= 0) ::close(wal_fd);
    wal_fd = -1;
    tables.clear();
    imm.clear();
    mem.reset();
  }

  void fill_stats(u64* out, int n) {
    u64 v[12] = {0};
    {
      std::lock_guard<std::mutex> g(mu);
      v[0] = stats.bloom_neg;
      v[1] = stats.bloom_pass;
      v[2] = stats.cache_hit;
      v[3] = stats.cache_miss;
      v[6] = stats.compactions;
      v[7] = tables.size();
      v[8] = mem ? mem->bytes : 0;
      v[9] = imm.size();
      // compaction backlog: tables beyond the trigger point — a sustained
      // non-zero value with compactions flat means the compactor is starved
      v[10] = tables.size() > compact_tables
                  ? tables.size() - compact_tables
                  : 0;
    }
    {
      std::lock_guard<std::mutex> g(wal_mu);
      v[4] = stats_wal_fsyncs;
      v[5] = stats.wal_records;
    }
    {
      std::lock_guard<std::mutex> g(trace.mu);
      v[11] = trace.dropped;
    }
    for (int i = 0; i < n && i < 12; i++) out[i] = v[i];
  }
};

}  // namespace

extern "C" {

void* lsm_open2(const char* dir, u64 flush_threshold, u64 cache_bytes,
                u64 compact_tables, u64 compact_rate_mbps) {
  Lsm* db = new Lsm();
  db->dir = dir;
  if (flush_threshold) db->flush_threshold = (size_t)flush_threshold;
  if (cache_bytes) db->cache.cap = (size_t)cache_bytes;
  if (compact_tables) db->compact_tables = (size_t)compact_tables;
  db->compact_rate_mbps = compact_rate_mbps;
  if (!db->open_dirs()) {
    delete db;
    return nullptr;
  }
  return db;
}

void* lsm_open(const char* dir, u64 flush_threshold) {
  return lsm_open2(dir, flush_threshold, 0, 0, 0);
}

void lsm_close(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  db->close_all();
  delete db;
}

int lsm_write_batch(void* h, const u8* payload, size_t len) {
  return static_cast<Lsm*>(h)->write_batch(payload, len);
}

u64 lsm_write_batch_async(void* h, const u8* payload, size_t len) {
  return static_cast<Lsm*>(h)->write_batch_async(payload, len);
}

int lsm_write_barrier(void* h, u64 seq) {
  return static_cast<Lsm*>(h)->write_barrier(seq);
}

int lsm_write_batch_partial(void* h, const u8* payload, size_t len,
                            int stage) {
  return static_cast<Lsm*>(h)->write_batch_partial(payload, len, stage);
}

int lsm_get(void* h, const u8* key, size_t klen, u8** val, size_t* vlen) {
  std::string out;
  int r = static_cast<Lsm*>(h)->get(
      std::string_view((const char*)key, klen), out);
  if (r != 1) return r;
  *val = (u8*)malloc(out.size() ? out.size() : 1);
  memcpy(*val, out.data(), out.size());
  *vlen = out.size();
  return 1;
}

int lsm_scan_prefix(void* h, const u8* prefix, size_t plen, u8** buf,
                    size_t* len) {
  std::string out;
  if (!static_cast<Lsm*>(h)->scan_prefix(
          std::string_view((const char*)prefix, plen), out))
    return -1;
  *buf = (u8*)malloc(out.size() ? out.size() : 1);
  memcpy(*buf, out.data(), out.size());
  *len = out.size();
  return 0;
}

int lsm_scan_from(void* h, const u8* prefix, size_t plen, const u8* after,
                  size_t alen, u64 limit, u8** buf, size_t* len) {
  std::string start((const char*)prefix, plen);
  if (alen) start.append((const char*)after, alen);
  std::string out;
  if (!static_cast<Lsm*>(h)->scan_from(
          std::string_view((const char*)prefix, plen), start,
          /*exclusive=*/alen > 0, limit, out))
    return -1;
  *buf = (u8*)malloc(out.size() ? out.size() : 1);
  memcpy(*buf, out.data(), out.size());
  *len = out.size();
  return 0;
}

int lsm_flush(void* h) { return static_cast<Lsm*>(h)->flush(); }

int lsm_compact_now(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  if (db->flush() != 0) return -1;
  if (!db->begin_manual_compaction()) return -1;
  bool ok = db->compact_once(/*swap=*/true);
  db->end_manual_compaction();
  return ok ? 0 : -1;
}

int lsm_compact_partial(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  if (db->flush() != 0) return -1;
  if (!db->begin_manual_compaction()) return -1;
  bool ok = db->compact_once(/*swap=*/false);
  db->end_manual_compaction();
  return ok ? 0 : -1;
}

int lsm_wait_compaction(void* h) {
  static_cast<Lsm*>(h)->wait_compaction();
  return 0;
}

void lsm_free(u8* p) { free(p); }

void lsm_stats(void* h, u64* out, int n) {
  static_cast<Lsm*>(h)->fill_stats(out, n);
}

// introspection for tests
u64 lsm_table_count(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return (u64)db->tables.size();
}

// -- flight recorder ---------------------------------------------------------

// Raw CLOCK_MONOTONIC now, for the Python clock-offset handshake.
u64 lsm_monotonic_ns() { return trace_now_ns(); }

// capacity 0 disables recording
void lsm_trace_configure(void* h, u64 capacity) {
  static_cast<Lsm*>(h)->trace.configure((size_t)capacity);
}

u64 lsm_trace_dropped(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  std::lock_guard<std::mutex> g(db->trace.mu);
  return db->trace.dropped;
}

// Two-call drain: size query with buf == NULL, then the copying call, which
// CONSUMES the ring. Same 32-byte big-endian record layout as the consensus
// engine's rt_trace_drain (u64 ts_ns, u64 dur_ns, u32 kind/tid/a/b).
// Background threads keep appending between the two calls, so callers
// should over-allocate; a too-small buffer returns the new size needed.
u64 lsm_trace_drain(void* h, u8* buf, u64 cap) {
  Lsm* db = static_cast<Lsm*>(h);
  TraceRing& r = db->trace;
  std::lock_guard<std::mutex> g(r.mu);
  std::string out;
  out.reserve(r.count * 32);
  if (r.count) {
    size_t start = (r.w + r.cap - r.count) % r.cap;
    for (size_t i = 0; i < r.count; i++) {
      const TraceEvent& e = r.buf[(start + i) % r.cap];
      trace_put64(out, e.ts_ns);
      trace_put64(out, e.dur_ns);
      trace_put32(out, e.kind);
      trace_put32(out, e.tid);
      trace_put32(out, e.a);
      trace_put32(out, e.b);
    }
  }
  if (!buf || out.size() > cap) return out.size();
  std::memcpy(buf, out.data(), out.size());
  r.count = 0;  // consumed (w stays: the ring keeps filling from there)
  return out.size();
}

int lsm_version() { return 6; }

}  // extern "C"
