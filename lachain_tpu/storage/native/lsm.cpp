// Native LSM storage engine — the role of the reference's RocksDB
// (/root/reference/src/Lachain.Storage/RocksDbContext.cs:23-60: one KV
// store, WAL-synced writes, atomic batches), re-designed small instead of
// vendored: a write-ahead log + sorted memtable + immutable sorted tables
// with full compaction and an atomically-rewritten manifest.
//
// Durability contract (matches SqliteKV's synchronous=FULL batches, which
// tests/test_storage_crash.py pins):
//   * write_batch appends ONE WAL record (CRC-framed) and fsyncs before
//     applying to the memtable — a batch is all-or-nothing across kill -9.
//   * memtable flush: SST written + fsynced, manifest rewritten via
//     tmp+rename+dir-fsync, and ONLY THEN the WAL is truncated. A crash at
//     any point replays the WAL over the previous manifest state.
//   * torn WAL tail (partial record / bad CRC) is discarded on open —
//     exactly the uncommitted batch.
//
// Reads: memtable, then tables newest->oldest (per-table sorted in-memory
// key index, values read with pread). Compaction: when the table count
// exceeds a threshold, ALL tables merge into one (newest wins; tombstones
// drop — nothing older can resurrect).
//
// Python binding: storage/lsm.py (ctypes). The batch wire format Python
// sends IS the WAL payload format, so the engine appends it verbatim.
#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

typedef uint8_t u8;
typedef uint32_t u32;
typedef uint64_t u64;

// CRC32 (IEEE, table-driven)
static u32 CRC_TAB[256];
static void crc_init() {
  static bool done = false;
  if (done) return;
  done = true;
  for (u32 i = 0; i < 256; i++) {
    u32 c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    CRC_TAB[i] = c;
  }
}
static u32 crc32(const u8* p, size_t n) {
  u32 c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = CRC_TAB[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static void put_u32(std::string& s, u32 v) {
  for (int i = 0; i < 4; i++) s.push_back((char)((v >> (8 * i)) & 0xFF));
}
static u32 get_u32(const u8* p) {
  return (u32)p[0] | ((u32)p[1] << 8) | ((u32)p[2] << 16) | ((u32)p[3] << 24);
}
static void put_u64(std::string& s, u64 v) {
  for (int i = 0; i < 8; i++) s.push_back((char)((v >> (8 * i)) & 0xFF));
}
static u64 get_u64(const u8* p) {
  u64 v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

static bool fsync_path(const std::string& path) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = ::fsync(fd) == 0;
  ::close(fd);
  return ok;
}

// batch payload: u32 count, then per op u8 type(0 put/1 del), u32 klen,
// key, u32 vlen, val (vlen=0 for deletes)
struct Op {
  bool del;
  std::string key, val;
};

static bool parse_batch(const u8* p, size_t n, std::vector<Op>& out) {
  if (n < 4) return false;
  u32 count = get_u32(p);
  size_t off = 4;
  out.clear();
  out.reserve(count);
  for (u32 i = 0; i < count; i++) {
    if (off + 5 > n) return false;
    u8 type = p[off];
    off += 1;
    u32 klen = get_u32(p + off);
    off += 4;
    if (off + klen + 4 > n) return false;
    std::string key((const char*)p + off, klen);
    off += klen;
    u32 vlen = get_u32(p + off);
    off += 4;
    if (off + vlen > n) return false;
    std::string val((const char*)p + off, vlen);
    off += vlen;
    out.push_back(Op{type == 1, std::move(key), std::move(val)});
  }
  return off == n;
}

// ---------------------------------------------------------------------------
// SSTable: [magic "LSST"][entries: u8 type, u32 klen, key, u32 vlen, val]*
//          [index: (u32 klen, key, u64 entry_off, u8 type, u32 vlen)*]
//          [u64 index_off][u32 index_count][u32 crc_of_index][magic "TSSL"]
// ---------------------------------------------------------------------------

struct TableEntry {
  std::string key;
  u64 off;    // offset of the VALUE bytes in the file
  u32 vlen;
  bool del;
};

struct Table {
  std::string path;
  int fd = -1;
  std::vector<TableEntry> index;  // sorted by key

  const TableEntry* find(const std::string& key) const {
    auto it = std::lower_bound(
        index.begin(), index.end(), key,
        [](const TableEntry& e, const std::string& k) { return e.key < k; });
    if (it == index.end() || it->key != key) return nullptr;
    return &*it;
  }
};

static bool write_table(const std::string& path,
                        const std::map<std::string, std::pair<bool, std::string>>& items,
                        bool drop_tombstones) {
  std::string body = "LSST";
  std::string index;
  u32 count = 0;
  for (auto& kv : items) {
    bool del = kv.second.first;
    if (del && drop_tombstones) continue;
    const std::string& val = kv.second.second;
    u64 entry_off;
    body.push_back(del ? 1 : 0);
    put_u32(body, (u32)kv.first.size());
    body += kv.first;
    put_u32(body, (u32)val.size());
    entry_off = body.size();
    body += val;
    put_u32(index, (u32)kv.first.size());
    index += kv.first;
    put_u64(index, entry_off);
    index.push_back(del ? 1 : 0);
    put_u32(index, (u32)val.size());
    count++;
  }
  u64 index_off = body.size();
  std::string footer;
  put_u64(footer, index_off);
  put_u32(footer, count);
  put_u32(footer, crc32((const u8*)index.data(), index.size()));
  footer += "TSSL";
  std::string all = body + index + footer;
  std::string tmp = path + ".tmp";
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  size_t done = 0;
  while (done < all.size()) {
    ssize_t w = ::write(fd, all.data() + done, all.size() - done);
    if (w <= 0) {
      ::close(fd);
      return false;
    }
    done += (size_t)w;
  }
  if (::fsync(fd) != 0) {
    ::close(fd);
    return false;
  }
  ::close(fd);
  if (::rename(tmp.c_str(), path.c_str()) != 0) return false;
  return true;
}

static bool load_table_inner(Table& t);

static bool load_table(Table& t) {
  // on ANY failure the fd must close here: the refusal path of open_dirs
  // runs per attempted open (a corrupted store is retried by operators,
  // and a long-lived process probing bad dirs must not leak fds)
  if (!load_table_inner(t)) {
    if (t.fd >= 0) ::close(t.fd);
    t.fd = -1;
    return false;
  }
  return true;
}

static bool load_table_inner(Table& t) {
  t.fd = ::open(t.path.c_str(), O_RDONLY);
  if (t.fd < 0) return false;
  off_t size = ::lseek(t.fd, 0, SEEK_END);
  if (size < (off_t)(4 + 20)) return false;
  u8 footer[20];
  if (::pread(t.fd, footer, 20, size - 20) != 20) return false;
  if (memcmp(footer + 16, "TSSL", 4) != 0) return false;
  u64 index_off = get_u64(footer);
  u32 count = get_u32(footer + 8);
  u32 want_crc = get_u32(footer + 12);
  if (index_off > (u64)size - 20) return false;
  size_t index_len = (size_t)((u64)size - 20 - index_off);
  std::vector<u8> ibuf(index_len);
  if (index_len &&
      ::pread(t.fd, ibuf.data(), index_len, (off_t)index_off) != (ssize_t)index_len)
    return false;
  if (crc32(ibuf.data(), index_len) != want_crc) return false;
  t.index.clear();
  t.index.reserve(count);
  size_t off = 0;
  for (u32 i = 0; i < count; i++) {
    if (off + 4 > index_len) return false;
    u32 klen = get_u32(ibuf.data() + off);
    off += 4;
    if (off + klen + 13 > index_len) return false;
    TableEntry e;
    e.key.assign((const char*)ibuf.data() + off, klen);
    off += klen;
    e.off = get_u64(ibuf.data() + off);
    off += 8;
    e.del = ibuf[off] == 1;
    off += 1;
    e.vlen = get_u32(ibuf.data() + off);
    off += 4;
    t.index.push_back(std::move(e));
  }
  return true;
}

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

struct Lsm {
  std::string dir;
  int wal_fd = -1;
  u64 next_seq = 1;
  size_t memtable_bytes = 0;
  size_t flush_threshold = 8u << 20;   // 8 MB memtable
  size_t compact_tables = 6;           // full-compact beyond this many
  std::map<std::string, std::pair<bool, std::string>> mem;  // key -> (del, val)
  std::vector<Table> tables;  // oldest .. newest
  std::mutex mu;

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string manifest_path() const { return dir + "/MANIFEST"; }
  std::string table_path(u64 seq) const {
    char buf[32];
    snprintf(buf, sizeof buf, "/sst_%012llu.dat", (unsigned long long)seq);
    return dir + buf;
  }

  void close_tables() {
    // single-sourced refusal/teardown contract: every open_dirs failure
    // path and close_all release table fds through here
    for (auto& t : tables)
      if (t.fd >= 0) ::close(t.fd);
    tables.clear();
  }

  bool write_manifest() {
    std::string body;
    for (auto& t : tables) {
      size_t slash = t.path.rfind('/');
      body += t.path.substr(slash + 1);
      body.push_back('\n');
    }
    std::string tmp = manifest_path() + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    if (::write(fd, body.data(), body.size()) != (ssize_t)body.size() ||
        ::fsync(fd) != 0) {
      ::close(fd);
      return false;
    }
    ::close(fd);
    if (::rename(tmp.c_str(), manifest_path().c_str()) != 0) return false;
    return fsync_path(dir);
  }

  bool apply_ops(const std::vector<Op>& ops) {
    for (auto& op : ops) {
      auto it = mem.find(op.key);
      if (it != mem.end())
        memtable_bytes -= it->first.size() + it->second.second.size();
      memtable_bytes += op.key.size() + op.val.size();
      mem[op.key] = {op.del, op.val};
    }
    return true;
  }

  bool open_dirs() {
    crc_init();
    ::mkdir(dir.c_str(), 0755);
    // manifest -> tables
    tables.clear();
    FILE* mf = fopen(manifest_path().c_str(), "r");
    if (mf) {
      char line[256];
      while (fgets(line, sizeof line, mf)) {
        size_t n = strlen(line);
        while (n && (line[n - 1] == '\n' || line[n - 1] == '\r')) line[--n] = 0;
        if (!n) continue;
        Table t;
        t.path = dir + "/" + line;
        if (!load_table(t)) {
          fclose(mf);
          close_tables();  // refuse without leaking fds
          return false;
        }
        // track the highest sequence for next_seq
        unsigned long long seq = 0;
        sscanf(line, "sst_%012llu.dat", &seq);
        if (seq >= next_seq) next_seq = seq + 1;
        tables.push_back(std::move(t));
      }
      fclose(mf);
    }
    // WAL replay: CRC-framed records; stop at the first bad one
    int rfd = ::open(wal_path().c_str(), O_RDONLY);
    if (rfd >= 0) {
      off_t size = ::lseek(rfd, 0, SEEK_END);
      std::vector<u8> buf((size_t)size);
      if (size > 0) {
        if (::pread(rfd, buf.data(), (size_t)size, 0) != (ssize_t)size) {
          ::close(rfd);
          close_tables();
          return false;
        }
      }
      ::close(rfd);
      size_t off = 0;
      while (off + 8 <= buf.size()) {
        u32 crc = get_u32(buf.data() + off);
        u32 len = get_u32(buf.data() + off + 4);
        if (off + 8 + len > buf.size()) break;  // torn tail
        if (crc32(buf.data() + off + 8, len) != crc) break;
        std::vector<Op> ops;
        if (!parse_batch(buf.data() + off + 8, len, ops)) break;
        apply_ops(ops);
        off += 8 + len;
      }
      // discard the torn tail ON DISK too: appending new records after
      // leftover garbage would make every future replay stop at the old
      // torn record and silently drop the acknowledged batches behind it
      if (off < buf.size()) {
        int tfd = ::open(wal_path().c_str(), O_WRONLY);
        bool ok = tfd >= 0 && ::ftruncate(tfd, (off_t)off) == 0 &&
                  ::fsync(tfd) == 0;
        if (tfd >= 0) ::close(tfd);
        if (!ok) {
          close_tables();
          return false;
        }
      }
    }
    wal_fd = ::open(wal_path().c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (wal_fd < 0) {
      close_tables();
      return false;
    }
    return true;
  }

  bool flush_memtable() {
    if (mem.empty()) return true;
    u64 seq = next_seq++;
    std::string path = table_path(seq);
    // tombstones must persist unless this becomes the ONLY table
    bool only = tables.empty();
    if (!write_table(path, mem, /*drop_tombstones=*/only)) return false;
    Table t;
    t.path = path;
    if (!load_table(t)) return false;
    tables.push_back(std::move(t));
    if (!write_manifest()) return false;
    // WAL content is now durable in the table: truncate
    ::close(wal_fd);
    wal_fd = ::open(wal_path().c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (wal_fd < 0) return false;
    if (::fsync(wal_fd) != 0) return false;
    mem.clear();
    memtable_bytes = 0;
    if (tables.size() > compact_tables) return compact();
    return true;
  }

  bool compact() {
    // full merge, newest wins; tombstones drop (nothing older remains)
    std::map<std::string, std::pair<bool, std::string>> merged;
    for (auto& t : tables) {  // oldest -> newest: later overwrites earlier
      for (auto& e : t.index) {
        if (e.del) {
          merged[e.key] = {true, std::string()};
        } else {
          std::string val(e.vlen, '\0');
          if (e.vlen &&
              ::pread(t.fd, &val[0], e.vlen, (off_t)e.off) != (ssize_t)e.vlen)
            return false;
          merged[e.key] = {false, std::move(val)};
        }
      }
    }
    u64 seq = next_seq++;
    std::string path = table_path(seq);
    if (!write_table(path, merged, /*drop_tombstones=*/true)) return false;
    Table t;
    t.path = path;
    if (!load_table(t)) return false;
    std::vector<Table> old;
    old.swap(tables);
    tables.push_back(std::move(t));
    if (!write_manifest()) return false;
    for (auto& o : old) {
      if (o.fd >= 0) ::close(o.fd);
      ::unlink(o.path.c_str());
    }
    return true;
  }

  bool write_batch(const u8* payload, size_t len) {
    std::lock_guard<std::mutex> g(mu);
    std::vector<Op> ops;
    if (!parse_batch(payload, len, ops)) return false;
    std::string rec;
    put_u32(rec, crc32(payload, len));
    put_u32(rec, (u32)len);
    rec.append((const char*)payload, len);
    size_t done = 0;
    while (done < rec.size()) {
      ssize_t w = ::write(wal_fd, rec.data() + done, rec.size() - done);
      if (w <= 0) return false;
      done += (size_t)w;
    }
    if (::fsync(wal_fd) != 0) return false;
    apply_ops(ops);
    if (memtable_bytes >= flush_threshold) return flush_memtable();
    return true;
  }

  // 1 found, 0 missing, -1 I/O error (a failed pread must NOT read as
  // "key absent" — the state layer would proceed on wrong state)
  int get(const std::string& key, std::string& out) {
    std::lock_guard<std::mutex> g(mu);
    auto it = mem.find(key);
    if (it != mem.end()) {
      if (it->second.first) return 0;
      out = it->second.second;
      return 1;
    }
    for (auto t = tables.rbegin(); t != tables.rend(); ++t) {
      const TableEntry* e = t->find(key);
      if (e == nullptr) continue;
      if (e->del) return 0;
      out.assign(e->vlen, '\0');
      if (e->vlen &&
          ::pread(t->fd, &out[0], e->vlen, (off_t)e->off) != (ssize_t)e->vlen)
        return -1;
      return 1;
    }
    return 0;
  }

  bool scan_prefix(const std::string& prefix, std::string& out) {
    std::lock_guard<std::mutex> g(mu);
    std::map<std::string, std::pair<bool, std::string>> found;
    for (auto& t : tables) {  // oldest -> newest
      auto it = std::lower_bound(
          t.index.begin(), t.index.end(), prefix,
          [](const TableEntry& e, const std::string& k) { return e.key < k; });
      for (; it != t.index.end(); ++it) {
        if (it->key.compare(0, prefix.size(), prefix) != 0) break;
        if (it->del) {
          found[it->key] = {true, std::string()};
        } else {
          std::string val(it->vlen, '\0');
          if (it->vlen && ::pread(t.fd, &val[0], it->vlen, (off_t)it->off) !=
                              (ssize_t)it->vlen)
            return false;
          found[it->key] = {false, std::move(val)};
        }
      }
    }
    for (auto it = mem.lower_bound(prefix); it != mem.end(); ++it) {
      if (it->first.compare(0, prefix.size(), prefix) != 0) break;
      found[it->first] = it->second;
    }
    out.clear();
    u32 count = 0;
    std::string body;
    for (auto& kv : found) {
      if (kv.second.first) continue;  // tombstone
      put_u32(body, (u32)kv.first.size());
      body += kv.first;
      put_u32(body, (u32)kv.second.second.size());
      body += kv.second.second;
      count++;
    }
    put_u32(out, count);
    out += body;
    return true;
  }

  void close_all() {
    std::lock_guard<std::mutex> g(mu);
    // durable by construction (WAL fsynced per batch); just release fds
    if (wal_fd >= 0) ::close(wal_fd);
    wal_fd = -1;
    close_tables();
  }
};

}  // namespace

extern "C" {

void* lsm_open(const char* dir, u64 flush_threshold) {
  Lsm* db = new Lsm();
  db->dir = dir;
  if (flush_threshold) db->flush_threshold = (size_t)flush_threshold;
  if (!db->open_dirs()) {
    delete db;
    return nullptr;
  }
  return db;
}

void lsm_close(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  db->close_all();
  delete db;
}

int lsm_write_batch(void* h, const u8* payload, size_t len) {
  return static_cast<Lsm*>(h)->write_batch(payload, len) ? 0 : -1;
}

int lsm_get(void* h, const u8* key, size_t klen, u8** val, size_t* vlen) {
  std::string out;
  int r = static_cast<Lsm*>(h)->get(std::string((const char*)key, klen), out);
  if (r != 1) return r;
  *val = (u8*)malloc(out.size() ? out.size() : 1);
  memcpy(*val, out.data(), out.size());
  *vlen = out.size();
  return 1;
}

int lsm_scan_prefix(void* h, const u8* prefix, size_t plen, u8** buf,
                    size_t* len) {
  std::string out;
  if (!static_cast<Lsm*>(h)->scan_prefix(
          std::string((const char*)prefix, plen), out))
    return -1;
  *buf = (u8*)malloc(out.size() ? out.size() : 1);
  memcpy(*buf, out.data(), out.size());
  *len = out.size();
  return 0;
}

int lsm_flush(void* h) {
  Lsm* db = static_cast<Lsm*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->flush_memtable() ? 0 : -1;
}

void lsm_free(u8* p) { free(p); }

// introspection for tests
u64 lsm_table_count(void* h) {
  // tables is mutated by flush/compaction under mu; an unguarded size()
  // read races a concurrent push_back/erase (UB on libstdc++ vectors)
  Lsm* db = static_cast<Lsm*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return (u64) db->tables.size();
}

int lsm_version() { return 1; }

}  // extern "C"
