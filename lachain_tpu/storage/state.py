"""State snapshot model: balances / contracts / storage / tx / events /
validators over the content-addressed trie.

Parity with the reference's 3-tier snapshot machinery
(/root/reference/src/Lachain.Storage/State/StateManager.cs:8-21 —
Committed / Approved / Pending; BlockchainSnapshot.cs aggregating 7
sub-snapshots; SnapshotManager approve/rollback/commit).

Redesign: because trie roots are immutable content-addressed values
(storage/trie.py), a snapshot is just a struct of root hashes + a write
buffer. "Approve" freezes the buffer into new roots; "commit" persists the
root set under the block height (SnapshotIndexRepository.cs role); "rollback"
is dropping the struct. No global mutex, no mutable tier state — the
functional idiom the TPU stack already uses.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.serialization import Reader, write_u64
from .kv import EntryPrefix, KVStore, prefixed
from .trie import EMPTY_ROOT, Trie

SUBTREES = (
    "balances",
    "contracts",
    "storage",
    "transactions",
    "blocks",
    "events",
    "validators",
)


@dataclass(frozen=True)
class StateRoots:
    """The 7 sub-roots; the block's state hash commits to all of them
    (reference: BlockchainSnapshot's sub-snapshot hash aggregation)."""

    balances: bytes = EMPTY_ROOT
    contracts: bytes = EMPTY_ROOT
    storage: bytes = EMPTY_ROOT
    transactions: bytes = EMPTY_ROOT
    blocks: bytes = EMPTY_ROOT
    events: bytes = EMPTY_ROOT
    validators: bytes = EMPTY_ROOT

    def state_hash(self) -> bytes:
        from ..crypto.hashes import keccak256

        return keccak256(b"".join(getattr(self, name) for name in SUBTREES))

    def encode(self) -> bytes:
        return b"".join(getattr(self, name) for name in SUBTREES)

    def all_roots(self) -> tuple:
        return tuple(getattr(self, name) for name in SUBTREES)

    @classmethod
    def decode(cls, data: bytes) -> "StateRoots":
        assert len(data) == 32 * len(SUBTREES)
        return cls(**{
            name: data[i * 32 : (i + 1) * 32] for i, name in enumerate(SUBTREES)
        })


class Snapshot:
    """Mutable working snapshot on top of immutable roots.

    Writes buffer in-memory; `freeze()` flushes them into the trie and
    returns new immutable StateRoots. Reads see buffered writes first
    (the reference's Pending tier).
    """

    # sentinel for "key was absent from the buffer" in undo entries —
    # distinct from None, which is the buffered-delete marker
    _ABSENT = object()

    def __init__(self, trie: Trie, roots: StateRoots):
        self._trie = trie
        self.base = roots
        self._writes: Dict[str, Dict[bytes, Optional[bytes]]] = {
            name: {} for name in SUBTREES
        }
        # undo log for delta checkpoints: one (tree, key, prior-buffer-value)
        # entry per buffer mutation; `checkpoint` is a position in this list
        self._undo: List[Tuple[str, bytes, object]] = []

    # -- typed access --------------------------------------------------------
    def get(self, tree: str, key: bytes) -> Optional[bytes]:
        buf = self._writes[tree]
        if key in buf:
            return buf[key]
        return self._trie.get(getattr(self.base, tree), key)

    def put(self, tree: str, key: bytes, value: bytes) -> None:
        buf = self._writes[tree]
        self._undo.append((tree, key, buf.get(key, Snapshot._ABSENT)))
        buf[key] = value

    def delete(self, tree: str, key: bytes) -> None:
        buf = self._writes[tree]
        self._undo.append((tree, key, buf.get(key, Snapshot._ABSENT)))
        buf[key] = None

    def freeze(self, workers: Optional[int] = None, stream=None) -> StateRoots:
        """Flush buffered writes -> new immutable roots (Approve). Bulk
        application: each shared internal node rebuilds once per freeze
        instead of once per key (Trie.apply_many; root bit-identical to
        the sequential replay for any worker count). `stream` forwards
        each completed subtrie's node batch to the caller as it finishes
        (StateManager.freeze_and_commit overlaps the WAL fsync with it)."""
        new_roots = {}
        for name in SUBTREES:
            new_roots[name] = self._trie.apply_many(
                getattr(self.base, name),
                self._writes[name],
                workers=workers,
                stream=stream,
            )
        return StateRoots(**new_roots)

    def discard(self) -> None:
        """Rollback: drop buffered writes (outstanding checkpoints die too)."""
        for name in SUBTREES:
            self._writes[name].clear()
        self._undo.clear()

    def checkpoint(self) -> int:
        """Mark the current buffer state for per-tx rollback (role of the
        reference's per-tx snapshot/approve/rollback loop,
        BlockManager.cs:371-560). O(1): the token is a position in the
        undo log — the old implementation deep-copied every buffered tree
        dict, which at 10k txs/block made per-tx checkpointing quadratic
        in block size. Checkpoints are LIFO: restoring an older token
        invalidates every younger one (both users — the per-tx loop in
        core/execution.py and the per-frame VM rollback in vm/vm.py —
        already nest strictly)."""
        return len(self._undo)

    def restore(self, cp: int) -> None:
        """Rewind the write buffer to a checkpoint token by popping the
        undo log back to its position; cost is O(writes since the
        checkpoint), not O(total buffered state)."""
        undo = self._undo
        writes = self._writes
        while len(undo) > cp:
            tree, key, prior = undo.pop()
            if prior is Snapshot._ABSENT:
                del writes[tree][key]
            else:
                writes[tree][key] = prior


class StateManager:
    """Committed-chain state keeper
    (reference: State/StateManager.cs + SnapshotIndexRepository.cs:1-104)."""

    # streamed-commit knobs: pending buffers smaller than stream_threshold
    # take the classic single-batch path (batch-splitting overhead isn't
    # worth it, and the crash-matrix workloads — which count write_batch
    # traversals as coordinates — stay on exactly one batch per commit);
    # larger ones ship in _STREAM_BATCH-item async WAL records
    stream_threshold = 4096
    _STREAM_BATCH = 4096

    def __init__(self, kv: KVStore):
        self._kv = kv
        self.trie = Trie(kv)
        self._committed: StateRoots = self._load_latest()
        # last commit's profile (streamed batches, fsync-wait seconds) for
        # the bench's commit-phase breakdown
        self.commit_stats: Dict[str, float] = {}

    # -- tiers ---------------------------------------------------------------
    @property
    def committed(self) -> StateRoots:
        return self._committed

    def new_snapshot(self, base: Optional[StateRoots] = None) -> Snapshot:
        return Snapshot(self.trie, base or self._committed)

    def _root_rows(self, height: int, roots: StateRoots) -> list:
        return [
            (
                prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(height)),
                roots.encode(),
            ),
            (prefixed(EntryPrefix.BLOCK_HEIGHT), write_u64(height)),
        ]

    def commit(self, height: int, roots: StateRoots) -> None:
        """Persist roots as the canonical state for `height` (checkpoint —
        every block is a checkpoint, SURVEY.md §5).

        Durability ordering invariant (both paths): NODES ARE NEVER
        DURABLE LATER THAN A ROOT RECORD REFERENCING THEM. Small pending
        buffers land in one atomic fsynced batch with the root index.
        Large ones stream as async WAL-record chunks that overlap each
        other's fsync, and the root rows go in a LAST synchronous batch
        after an explicit barrier — a crash mid-stream leaves only
        orphan content-addressed nodes (no root record): fsck-clean,
        replay recommits them, shrink reclaims them."""
        import time as _time

        nodes = self.trie.peek_pending()
        root_rows = self._root_rows(height, roots)
        streamed = 0
        t0 = _time.perf_counter()
        if (
            getattr(self._kv, "supports_async_batches", False)
            and len(nodes) >= self.stream_threshold
        ):
            from .crashpoints import crash_point

            ticket = None
            for i in range(0, len(nodes), self._STREAM_BATCH):
                ticket = self._kv.write_batch_async(
                    nodes[i : i + self._STREAM_BATCH]
                )
                streamed += 1
                crash_point("trie.merkle.subtree_streamed")
            # the WAL is append-ordered, so the final batch's ack would
            # already imply these; the explicit barrier keeps the invariant
            # independent of that engine detail
            self._kv.write_barrier(ticket)
            self._kv.write_batch(root_rows)
        else:
            self._kv.write_batch(nodes + root_rows)
        # only after the batch is durable: a failed write_batch must keep
        # the buffer (it holds the only copy of the nodes)
        self.trie.confirm_pending(nodes)
        self._committed = roots
        self.commit_stats = {
            "wal_fsync_s": _time.perf_counter() - t0,
            "streamed_batches": streamed,
            "nodes": len(nodes),
        }

    def freeze_and_commit(
        self, height: int, snap: Snapshot, workers: Optional[int] = None
    ) -> StateRoots:
        """Freeze + commit with full fsync overlap: each subtrie's node
        batch is submitted to the WAL writer AS ITS WORKER FINISHES, so
        the disk absorbs completed subtries while the remaining ones are
        still hashing. The root-referencing rows are written LAST, in a
        synchronous batch behind a barrier — same ordering invariant as
        commit(). Engines without async batches just freeze-then-commit."""
        import time as _time

        kv = self._kv
        if not (
            getattr(kv, "supports_async_batches", False)
            and sum(len(w) for w in snap._writes.values())
            >= self.stream_threshold
        ):
            roots = snap.freeze(workers=workers)
            self.commit(height, roots)
            return roots

        from .crashpoints import crash_point

        streamed_keys: set = set()
        tickets: list = []
        fsync_wait = [0.0]

        def stream(items):
            t0 = _time.perf_counter()
            tickets.append(kv.write_batch_async(items))
            fsync_wait[0] += _time.perf_counter() - t0
            streamed_keys.update(k for k, _ in items)
            crash_point("trie.merkle.subtree_streamed")

        roots = snap.freeze(workers=workers, stream=stream)
        nodes = self.trie.peek_pending()
        remaining = [(k, v) for k, v in nodes if k not in streamed_keys]
        t0 = _time.perf_counter()
        if tickets:
            kv.write_barrier(tickets[-1])
        kv.write_batch(remaining + self._root_rows(height, roots))
        self.trie.confirm_pending(nodes)
        self._committed = roots
        self.commit_stats = {
            "wal_fsync_s": fsync_wait[0] + _time.perf_counter() - t0,
            "streamed_batches": len(tickets),
            "nodes": len(nodes),
        }
        return roots

    def roots_at(self, height: int) -> Optional[StateRoots]:
        enc = self._kv.get(prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(height)))
        return StateRoots.decode(enc) if enc else None

    def rollback_to(self, height: int) -> StateRoots:
        """Restore an older checkpoint (reference --RollBackTo,
        Application.cs:119-127)."""
        roots = self.roots_at(height)
        if roots is None:
            raise KeyError(f"no snapshot at height {height}")
        self._kv.put(prefixed(EntryPrefix.BLOCK_HEIGHT), write_u64(height))
        self._committed = roots
        return roots

    def committed_height(self) -> Optional[int]:
        enc = self._kv.get(prefixed(EntryPrefix.BLOCK_HEIGHT))
        return Reader(enc).u64() if enc else None

    def _load_latest(self) -> StateRoots:
        h = self.committed_height()
        if h is None:
            return StateRoots()
        roots = self.roots_at(h)
        return roots if roots is not None else StateRoots()
