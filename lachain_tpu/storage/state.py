"""State snapshot model: balances / contracts / storage / tx / events /
validators over the content-addressed trie.

Parity with the reference's 3-tier snapshot machinery
(/root/reference/src/Lachain.Storage/State/StateManager.cs:8-21 —
Committed / Approved / Pending; BlockchainSnapshot.cs aggregating 7
sub-snapshots; SnapshotManager approve/rollback/commit).

Redesign: because trie roots are immutable content-addressed values
(storage/trie.py), a snapshot is just a struct of root hashes + a write
buffer. "Approve" freezes the buffer into new roots; "commit" persists the
root set under the block height (SnapshotIndexRepository.cs role); "rollback"
is dropping the struct. No global mutex, no mutable tier state — the
functional idiom the TPU stack already uses.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from ..utils.serialization import Reader, write_bytes, write_u64
from .kv import EntryPrefix, KVStore, prefixed
from .trie import EMPTY_ROOT, Trie

SUBTREES = (
    "balances",
    "contracts",
    "storage",
    "transactions",
    "blocks",
    "events",
    "validators",
)


@dataclass(frozen=True)
class StateRoots:
    """The 7 sub-roots; the block's state hash commits to all of them
    (reference: BlockchainSnapshot's sub-snapshot hash aggregation)."""

    balances: bytes = EMPTY_ROOT
    contracts: bytes = EMPTY_ROOT
    storage: bytes = EMPTY_ROOT
    transactions: bytes = EMPTY_ROOT
    blocks: bytes = EMPTY_ROOT
    events: bytes = EMPTY_ROOT
    validators: bytes = EMPTY_ROOT

    def state_hash(self) -> bytes:
        from ..crypto.hashes import keccak256

        return keccak256(b"".join(getattr(self, name) for name in SUBTREES))

    def encode(self) -> bytes:
        return b"".join(getattr(self, name) for name in SUBTREES)

    def all_roots(self) -> tuple:
        return tuple(getattr(self, name) for name in SUBTREES)

    @classmethod
    def decode(cls, data: bytes) -> "StateRoots":
        assert len(data) == 32 * len(SUBTREES)
        return cls(**{
            name: data[i * 32 : (i + 1) * 32] for i, name in enumerate(SUBTREES)
        })


class Snapshot:
    """Mutable working snapshot on top of immutable roots.

    Writes buffer in-memory; `freeze()` flushes them into the trie and
    returns new immutable StateRoots. Reads see buffered writes first
    (the reference's Pending tier).
    """

    # sentinel for "key was absent from the buffer" in undo entries —
    # distinct from None, which is the buffered-delete marker
    _ABSENT = object()

    def __init__(self, trie: Trie, roots: StateRoots):
        self._trie = trie
        self.base = roots
        self._writes: Dict[str, Dict[bytes, Optional[bytes]]] = {
            name: {} for name in SUBTREES
        }
        # undo log for delta checkpoints: one (tree, key, prior-buffer-value)
        # entry per buffer mutation; `checkpoint` is a position in this list
        self._undo: List[Tuple[str, bytes, object]] = []

    # -- typed access --------------------------------------------------------
    def get(self, tree: str, key: bytes) -> Optional[bytes]:
        buf = self._writes[tree]
        if key in buf:
            return buf[key]
        return self._trie.get(getattr(self.base, tree), key)

    def put(self, tree: str, key: bytes, value: bytes) -> None:
        buf = self._writes[tree]
        self._undo.append((tree, key, buf.get(key, Snapshot._ABSENT)))
        buf[key] = value

    def delete(self, tree: str, key: bytes) -> None:
        buf = self._writes[tree]
        self._undo.append((tree, key, buf.get(key, Snapshot._ABSENT)))
        buf[key] = None

    def freeze(self) -> StateRoots:
        """Flush buffered writes -> new immutable roots (Approve). Bulk
        application: each shared internal node rebuilds once per freeze
        instead of once per key (Trie.apply_many; root bit-identical to
        the sequential replay)."""
        new_roots = {}
        for name in SUBTREES:
            new_roots[name] = self._trie.apply_many(
                getattr(self.base, name), self._writes[name]
            )
        return StateRoots(**new_roots)

    def discard(self) -> None:
        """Rollback: drop buffered writes (outstanding checkpoints die too)."""
        for name in SUBTREES:
            self._writes[name].clear()
        self._undo.clear()

    def checkpoint(self) -> int:
        """Mark the current buffer state for per-tx rollback (role of the
        reference's per-tx snapshot/approve/rollback loop,
        BlockManager.cs:371-560). O(1): the token is a position in the
        undo log — the old implementation deep-copied every buffered tree
        dict, which at 10k txs/block made per-tx checkpointing quadratic
        in block size. Checkpoints are LIFO: restoring an older token
        invalidates every younger one (both users — the per-tx loop in
        core/execution.py and the per-frame VM rollback in vm/vm.py —
        already nest strictly)."""
        return len(self._undo)

    def restore(self, cp: int) -> None:
        """Rewind the write buffer to a checkpoint token by popping the
        undo log back to its position; cost is O(writes since the
        checkpoint), not O(total buffered state)."""
        undo = self._undo
        writes = self._writes
        while len(undo) > cp:
            tree, key, prior = undo.pop()
            if prior is Snapshot._ABSENT:
                del writes[tree][key]
            else:
                writes[tree][key] = prior


class StateManager:
    """Committed-chain state keeper
    (reference: State/StateManager.cs + SnapshotIndexRepository.cs:1-104)."""

    def __init__(self, kv: KVStore):
        self._kv = kv
        self.trie = Trie(kv)
        self._committed: StateRoots = self._load_latest()

    # -- tiers ---------------------------------------------------------------
    @property
    def committed(self) -> StateRoots:
        return self._committed

    def new_snapshot(self, base: Optional[StateRoots] = None) -> Snapshot:
        return Snapshot(self.trie, base or self._committed)

    def commit(self, height: int, roots: StateRoots) -> None:
        """Persist roots as the canonical state for `height` (checkpoint —
        every block is a checkpoint, SURVEY.md §5). The trie's buffered
        node writes land in the SAME atomic fsynced batch as the root
        index, so a crash can never leave a root without its nodes."""
        nodes = self.trie.peek_pending()
        self._kv.write_batch(
            nodes
            + [
                (
                    prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(height)),
                    roots.encode(),
                ),
                (prefixed(EntryPrefix.BLOCK_HEIGHT), write_u64(height)),
            ]
        )
        # only after the batch is durable: a failed write_batch must keep
        # the buffer (it holds the only copy of the nodes)
        self.trie.confirm_pending(nodes)
        self._committed = roots

    def roots_at(self, height: int) -> Optional[StateRoots]:
        enc = self._kv.get(prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(height)))
        return StateRoots.decode(enc) if enc else None

    def rollback_to(self, height: int) -> StateRoots:
        """Restore an older checkpoint (reference --RollBackTo,
        Application.cs:119-127)."""
        roots = self.roots_at(height)
        if roots is None:
            raise KeyError(f"no snapshot at height {height}")
        self._kv.put(prefixed(EntryPrefix.BLOCK_HEIGHT), write_u64(height))
        self._committed = roots
        return roots

    def committed_height(self) -> Optional[int]:
        enc = self._kv.get(prefixed(EntryPrefix.BLOCK_HEIGHT))
        return Reader(enc).u64() if enc else None

    def _load_latest(self) -> StateRoots:
        h = self.committed_height()
        if h is None:
            return StateRoots()
        roots = self.roots_at(h)
        return roots if roots is not None else StateRoots()
