"""DbShrink: prune trie nodes unreachable from recent checkpoints.

Parity with the reference's DbShrink
(/root/reference/src/Lachain.Storage/DbCompact/DbShrink.cs:118-203 +
DbShrinkRepository.cs): the content-addressed trie never garbage-collects on
its own — every historical root keeps its nodes alive — so long-running
nodes prune snapshots older than a retention depth with a staged,
RESUMABLE mark-and-sweep:

  stage MARK   — walk every retained root (heights in [cutoff, tip]) and
                 persist a mark entry per reachable node hash; progress is
                 checkpointed per height so a crash resumes where it left
  stage SWEEP  — scan all trie nodes, delete unmarked ones
  stage CLEAN  — drop the mark entries + stale snapshot-index rows

The stage and cursor live in the KV (SHRINK_STATE), mirroring the
reference's DbShrinkStatus/DbShrinkDepositBlock bookkeeping.
"""
from __future__ import annotations

import json
import logging
from typing import Optional

from .crashpoints import crash_point
from .kv import EntryPrefix, KVStore, prefixed
from .state import StateManager, StateRoots
from .trie import EMPTY_ROOT, InternalNode

logger = logging.getLogger(__name__)

_STATE_KEY = prefixed(EntryPrefix.SHRINK_STATE)
_MARK = EntryPrefix.SHRINK_MARK


class DbShrink:
    def __init__(self, state: StateManager, kv: KVStore):
        self.state = state
        self.kv = kv

    # -- progress bookkeeping -----------------------------------------------

    def _load_progress(self) -> Optional[dict]:
        raw = self.kv.get(_STATE_KEY)
        return json.loads(raw.decode()) if raw else None

    def _save_progress(self, p: dict) -> None:
        self.kv.put(_STATE_KEY, json.dumps(p).encode())

    # -- the staged shrink ---------------------------------------------------

    def shrink(self, retain_depth: int) -> dict:
        """Prune everything below (tip - retain_depth). Safe to re-invoke
        after a crash: resumes from the persisted stage/cursor. Returns
        stats {marked, swept, cutoff}."""
        tip = self.state.committed_height()
        if tip is None:
            return {"marked": 0, "swept": 0, "cutoff": 0}
        progress = self._load_progress()
        if progress is None:
            cutoff = max(0, tip - retain_depth)
            progress = {
                "stage": "mark",
                "cutoff": cutoff,
                "tip": tip,
                "next_height": cutoff,
                "marked": 0,
            }
            self._save_progress(progress)
        # a resumed run keeps its original CUTOFF (marks below it were never
        # made) but must extend the mark range to the CURRENT tip: blocks
        # committed between crash and resume would otherwise have their trie
        # nodes swept as unmarked — corrupting the newest state. Extra
        # marking is always safe; missing marks never are.
        cutoff = progress["cutoff"]
        if tip > progress["tip"]:
            old_tip = progress["tip"]
            progress["tip"] = tip
            if progress["stage"] != "mark":
                # the sweep/clean stages must never run with unmarked recent
                # heights: fall back to marking the delta first
                progress["stage"] = "mark"
                progress["next_height"] = old_tip + 1
            self._save_progress(progress)
        tip = progress["tip"]

        if progress["stage"] == "mark":
            while True:
                for height in range(progress["next_height"], tip + 1):
                    roots = self.state.roots_at(height)
                    if roots is not None:
                        progress["marked"] += self._mark_roots(roots)
                    progress["next_height"] = height + 1
                    self._save_progress(progress)  # per-height resume point
                    crash_point("shrink.mark.height")
                # Re-check the tip before committing to sweep: marking takes
                # real time, and a block committed meanwhile (threaded caller,
                # CLI racing a live node) would have its nodes swept as
                # unmarked. Loop until the tip is stable across a full mark
                # pass — the same extend-don't-shrink rule as the resume path.
                # shrink() itself is synchronous, so an in-event-loop caller
                # cannot be raced past this point.
                new_tip = self.state.committed_height()
                if new_tip is None or new_tip <= tip:
                    break
                progress["tip"] = tip = new_tip
                self._save_progress(progress)
            progress["stage"] = "sweep"
            self._save_progress(progress)

        if progress["stage"] == "sweep":
            crash_point("shrink.sweep.pre")
            swept = self._sweep(progress)
            progress["swept"] = progress.get("swept", 0) + swept
            progress["stage"] = "clean"
            self._save_progress(progress)

        if progress["stage"] == "clean":
            crash_point("shrink.clean.pre")
            self._clean_marks()
            # drop pruned heights from the snapshot index: scan live index
            # rows (O(retained) after the first shrink) instead of probing
            # every height since genesis
            idx_prefix = prefixed(EntryPrefix.SNAPSHOT_INDEX)
            stale = []
            for key, _ in self.kv.scan_prefix(idx_prefix):
                height = int.from_bytes(key[len(idx_prefix):], "big")
                if height < cutoff:
                    stale.append(key)
            for key in stale:
                self.kv.delete(key)
            self.kv.delete(_STATE_KEY)

        stats = {
            "marked": progress.get("marked", 0),
            "swept": progress.get("swept", 0),
            "cutoff": cutoff,
        }
        logger.info("db shrink done: %s", stats)
        return stats

    # -- stages --------------------------------------------------------------

    def _mark_roots(self, roots: StateRoots) -> int:
        """DFS from every tree root of a snapshot; marks persisted in the KV
        (a node already marked prunes the whole subtree walk — structural
        sharing makes repeated roots cheap)."""
        marked = 0
        stack = [r for r in roots.all_roots() if r != EMPTY_ROOT]
        while stack:
            h = stack.pop()
            mark_key = prefixed(_MARK, h)
            if self.kv.get(mark_key) is not None:
                continue
            self.kv.put(mark_key, b"\x01")
            marked += 1
            node = self.state.trie._load(h)
            if isinstance(node, InternalNode):
                stack.extend(
                    c for c in node.children if c != EMPTY_ROOT
                )
        return marked

    def _sweep(self, progress: dict) -> int:
        node_prefix = prefixed(EntryPrefix.TRIE_NODE)
        doomed = []
        for key, _ in self.kv.scan_prefix(node_prefix):
            h = key[len(node_prefix):]
            if self.kv.get(prefixed(_MARK, h)) is None:
                doomed.append(key)
        # the scan takes real time too: a block committed during it (threaded
        # caller) has unmarked nodes sitting in `doomed`. Mark the tip delta
        # now and drop the newly marked keys before deleting. A commit landing
        # after THIS point and before the deletes finish is out of scope —
        # shrink() must not race commits from another thread/process past
        # here (the KV is single-writer; the node calls shrink on its own
        # event-loop thread where the whole run is atomic).
        new_tip = self.state.committed_height()
        if new_tip is not None and new_tip > progress["tip"]:
            for height in range(progress["tip"] + 1, new_tip + 1):
                roots = self.state.roots_at(height)
                if roots is not None:
                    progress["marked"] += self._mark_roots(roots)
            progress["tip"] = new_tip
            self._save_progress(progress)
            doomed = [
                k for k in doomed
                if self.kv.get(prefixed(_MARK, k[len(node_prefix):])) is None
            ]
        for key in doomed:
            self.kv.delete(key)
        # pruned nodes may still sit in the trie's LRU cache; a fresh run
        # only ever reads retained roots, but drop the cache for hygiene
        self.state.trie.clear_cache()
        return len(doomed)

    def _clean_marks(self) -> None:
        for key, _ in list(self.kv.scan_prefix(prefixed(_MARK))):
            self.kv.delete(key)
