"""LsmKV — the native LSM storage engine behind the KVStore seam.

Role of the reference's RocksDB context
(/root/reference/src/Lachain.Storage/RocksDbContext.cs:23-60): a log-
structured KV store with WAL-synced atomic batches. The engine itself is
C++ (storage/native/lsm.cpp, format v2): CRC-framed WAL segments written
and fsynced by a pipeline thread (group commit; the batch ack fires only
after the fsync) -> arena/skiplist memtable -> block-based SSTables with
per-table bloom filters and a shared block cache, flushed and compacted by
rate-limited background threads. Durability contract matches SqliteKV's
synchronous=FULL batches (same kill -9 guarantees, tests/test_lsm.py +
tests/test_crashpoints.py shape).

Single-op put/delete are WAL-synced one-op batches — same semantics as
SqliteKV's autocommit puts, with the fsync cost that implies; bulk paths
use write_batch exactly as they do over SqliteKV.

Crash-point sites (tests/test_crashpoints.py matrix): beyond the generic
kv.write_batch.pre/.post, write_batch visits three engine-specific points
that leave REAL torn state via the native partial-execution debug APIs
before dying — lsm.wal.encoded (torn record tail in the active WAL
segment), lsm.wal.fsynced (record durable but never acked/applied), and
lsm.compact.mid (merged SST renamed into place but the manifest swap
lost). Identical bytes on disk in both harness modes.

Set LACHAIN_LSM_LIB to load an alternate engine build (the ASan/UBSan
gate in tests/native/sanitize.sh runs the storage test slice against a
sanitizer-instrumented libllsm).
"""
from __future__ import annotations

import ctypes
import os
import signal
import struct
import subprocess
import threading
import weakref
from typing import Dict, Iterator, List, Optional, Tuple

from .kv import KVStore

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libllsm.so")
_lib_cache: list = [None]

# lsm_stats() slot order (keep in sync with Lsm::fill_stats)
_STAT_FIELDS = (
    "bloom_hits",       # filter ruled a table out (saved a block fetch)
    "bloom_misses",     # filter passed; a data block was consulted
    "cache_hits",
    "cache_misses",
    "wal_fsyncs",
    "wal_records",
    "compactions",
    "table_count",
    "memtable_bytes",
    "imm_memtables",
    "compact_backlog",  # tables beyond the compaction trigger point
    "trace_dropped",    # flight-recorder ring evictions
)

# lsm.cpp trace record contract: 32-byte big-endian records, same frame as
# the consensus engine (u64 ts_ns, u64 dur_ns, u32 kind, u32 tid, u32 a, b)
_TRACE_RECORD = struct.Struct(">QQIIII")
_LK_NAMES = {
    20: "wal_encode",  # a = payload bytes
    21: "wal_fsync",   # a = group-commit records, b = bytes written
    22: "memtable_seal",  # a = bytes, b = new WAL segment
    23: "memtable_flush",  # a = bytes, b = sst seq
    24: "compaction",  # a = input tables, b = output seq
    25: "wait:fsync",  # caller blocked on durability; a = wait resource
}
_LT_NAMES = {0: "caller", 1: "wal-writer", 2: "flusher", 3: "compactor"}
# bytes-per-group-commit spread widely; record counts are small integers
_GROUP_COMMIT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
_next_trace_pid = iter(range(3, 1 << 30))  # pid 1 = python, 2 = consensus


def _load_lib():
    if _lib_cache[0] is not None:
        return _lib_cache[0]
    override = os.environ.get("LACHAIN_LSM_LIB")
    lib_path = override or _LIB_PATH
    if not override:
        sources = [
            os.path.join(_NATIVE_DIR, "lsm.cpp"),
            os.path.join(_NATIVE_DIR, "Makefile"),
        ]
        if not os.path.exists(_LIB_PATH) or any(
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(s) for s in sources
        ):
            subprocess.run(
                ["make", "-s", "-C", _NATIVE_DIR], check=True,
                capture_output=True,
            )
    lib = ctypes.CDLL(lib_path)
    lib.lsm_open.restype = ctypes.c_void_p
    lib.lsm_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.lsm_open2.restype = ctypes.c_void_p
    lib.lsm_open2.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.lsm_close.argtypes = [ctypes.c_void_p]
    lib.lsm_write_batch.restype = ctypes.c_int
    lib.lsm_write_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.lsm_write_batch_async.restype = ctypes.c_uint64
    lib.lsm_write_batch_async.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.lsm_write_barrier.restype = ctypes.c_int
    lib.lsm_write_barrier.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.lsm_write_batch_partial.restype = ctypes.c_int
    lib.lsm_write_batch_partial.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.lsm_get.restype = ctypes.c_int
    lib.lsm_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.lsm_scan_prefix.restype = ctypes.c_int
    lib.lsm_scan_prefix.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.lsm_scan_from.restype = ctypes.c_int
    lib.lsm_scan_from.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.lsm_flush.restype = ctypes.c_int
    lib.lsm_flush.argtypes = [ctypes.c_void_p]
    lib.lsm_compact_now.restype = ctypes.c_int
    lib.lsm_compact_now.argtypes = [ctypes.c_void_p]
    lib.lsm_compact_partial.restype = ctypes.c_int
    lib.lsm_compact_partial.argtypes = [ctypes.c_void_p]
    lib.lsm_wait_compaction.restype = ctypes.c_int
    lib.lsm_wait_compaction.argtypes = [ctypes.c_void_p]
    lib.lsm_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.lsm_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
    lib.lsm_table_count.restype = ctypes.c_uint64
    lib.lsm_table_count.argtypes = [ctypes.c_void_p]
    lib.lsm_version.restype = ctypes.c_int
    assert lib.lsm_version() == 6
    lib.lsm_monotonic_ns.restype = ctypes.c_uint64
    lib.lsm_monotonic_ns.argtypes = []
    lib.lsm_trace_configure.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.lsm_trace_dropped.restype = ctypes.c_uint64
    lib.lsm_trace_dropped.argtypes = [ctypes.c_void_p]
    lib.lsm_trace_drain.restype = ctypes.c_uint64
    lib.lsm_trace_drain.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.c_uint64,
    ]
    _lib_cache[0] = lib
    return lib


def _encode_batch(
    puts: List[Tuple[bytes, bytes]], deletes: List[bytes]
) -> bytes:
    parts = [(len(puts) + len(deletes)).to_bytes(4, "little")]
    for k, v in puts:
        parts.append(
            b"\x00" + len(k).to_bytes(4, "little") + k
            + len(v).to_bytes(4, "little") + v
        )
    for k in deletes:
        parts.append(
            b"\x01" + len(k).to_bytes(4, "little") + k + b"\x00\x00\x00\x00"
        )
    return b"".join(parts)


class LsmKV(KVStore):
    """Durable KV on the native LSM engine (drop-in for SqliteKV)."""

    # WAL runs on its own writer thread -> write_batch_async genuinely
    # overlaps the record's encode+fsync with the caller's next work
    supports_async_batches = True

    def __init__(
        self,
        path: str,
        flush_threshold: int = 8 << 20,
        cache_bytes: int = 0,
        compact_tables: int = 0,
        compact_rate_mbps: int = 0,
    ):
        self._lib = _load_lib()
        self._lock = threading.Lock()
        self._h = self._lib.lsm_open2(
            path.encode(), flush_threshold, cache_bytes,
            compact_tables, compact_rate_mbps,
        )
        if not self._h:
            raise IOError(f"cannot open LSM store at {path!r}")
        # flight recorder: size the engine ring, align its clock, register
        # with the merged tracer (own pid per store; engine thread roles
        # become named rows in the Chrome export)
        from ..utils import tracing

        self._trace_offset = tracing.clock_offset(self._lib.lsm_monotonic_ns)
        self._trace_dropped_seen = 0
        self._trace_pid = next(_next_trace_pid)
        self._trace_source = f"lsm-{os.path.basename(path) or path}-{id(self):x}"
        self._lib.lsm_trace_configure(self._h, tracing.DEFAULT_CAPACITY)
        ref = weakref.ref(self)
        tracing.register_native_source(
            self._trace_source,
            lambda: [] if ref() is None else ref()._drain_trace(),
        )

    # -- flight recorder -------------------------------------------------------
    def trace_configure(self, capacity: int) -> None:
        """Resize the engine-side trace ring; 0 disables recording."""
        with self._lock:
            if self._h:
                self._lib.lsm_trace_configure(self._h, max(int(capacity), 0))

    def _decode_trace(self, raw: bytes) -> List[dict]:
        evs: List[dict] = []
        for i in range(0, len(raw) - (len(raw) % 32), 32):
            ts, dur, kind, tid, a, b = _TRACE_RECORD.unpack_from(raw, i)
            name = _LK_NAMES.get(kind, str(kind))
            is_wait = kind == 25  # LK_WAIT: caller-side durability stall
            evs.append(
                {
                    "name": name,
                    "cat": "native.wait" if is_wait else "native.lsm",
                    "start": ts / 1e9 + self._trace_offset,
                    "end": (ts + dur) / 1e9 + self._trace_offset,
                    "pid": self._trace_pid,
                    "pname": self._trace_source.rsplit("-", 1)[0],
                    "tid": tid,
                    "tname": _LT_NAMES.get(tid, str(tid)),
                    "args": {"resource": "fsync"} if is_wait
                    else {"a": a, "b": b},
                }
            )
            if is_wait:
                from ..utils import metrics

                metrics.observe_hist(
                    "wait_seconds", dur / 1e9, labels={"resource": "fsync"}
                )
            if kind == 21:  # LK_WAL_FSYNC: the never-published v2 numbers
                from ..utils import metrics

                metrics.observe_hist("lsm_wal_fsync_seconds", dur / 1e9)
                metrics.observe_hist(  # lint-allow: metric-name dimensionless record-count distribution
                    "lsm_wal_group_commit_records",
                    a,
                    buckets=_GROUP_COMMIT_BUCKETS,
                )
        return evs

    def _drain_trace(self) -> List[dict]:
        """Consume the engine trace ring -> merged-tracer event dicts;
        feeds the WAL fsync/group-commit histograms and publishes native
        ring-drop growth as trace_events_dropped_total deltas."""
        evs: List[dict] = []
        with self._lock:
            if not self._h:
                return []
            for _ in range(4):
                need = self._lib.lsm_trace_drain(self._h, None, 0)
                if need == 0:
                    break
                buf = (ctypes.c_ubyte * (need + 4096))()
                got = self._lib.lsm_trace_drain(self._h, buf, len(buf))
                if got <= len(buf):
                    evs = self._decode_trace(bytes(buf[:got]))
                    break
            dropped = int(self._lib.lsm_trace_dropped(self._h))
        if dropped > self._trace_dropped_seen:
            from ..utils import metrics

            metrics.inc(
                "trace_events_dropped_total",
                dropped - self._trace_dropped_seen,
                labels={"source": "lsm"},
            )
            self._trace_dropped_seen = dropped
        return evs

    def get(self, key: bytes) -> Optional[bytes]:
        val = ctypes.POINTER(ctypes.c_ubyte)()
        vlen = ctypes.c_size_t(0)
        r = self._lib.lsm_get(
            self._h, key, len(key), ctypes.byref(val), ctypes.byref(vlen)
        )
        if r < 0:
            raise IOError(f"LSM read failed for key {key!r}")
        if r != 1:
            return None
        try:
            return ctypes.string_at(val, vlen.value)
        finally:
            self._lib.lsm_free(val)

    def put(self, key: bytes, value: bytes) -> None:
        self.write_batch([(key, value)])

    def delete(self, key: bytes) -> None:
        self.write_batch([], [key])

    # engine-specific crash sites: leave genuinely torn native state via
    # the partial-execution debug APIs, THEN die the way the armed point
    # asks (InjectedCrash or real SIGKILL). The disk image is identical in
    # both modes, which is what makes the matrix verdicts comparable.
    _TORN_SITES = (("lsm.wal.encoded", 0), ("lsm.wal.fsynced", 1))

    def _visit_torn_sites(self, payload: bytes) -> None:
        from . import crashpoints

        session = crashpoints.active()
        if session is None:
            return
        for name, stage in self._TORN_SITES:
            point = session.visit(name)
            if point is not None:
                with self._lock:
                    rc = self._lib.lsm_write_batch_partial(
                        self._h, payload, len(payload), stage
                    )
                if rc != 0:
                    raise IOError(f"LSM partial write failed at {name}")
                self._die(point, name)
        point = session.visit("lsm.compact.mid")
        if point is not None:
            with self._lock:
                if self._lib.lsm_compact_partial(self._h) != 0:
                    raise IOError("LSM partial compaction failed")
            self._die(point, "lsm.compact.mid")

    @staticmethod
    def _die(point, name: str) -> None:
        from .crashpoints import MODE_SIGKILL, InjectedCrash

        if point.mode == MODE_SIGKILL:
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedCrash(name, point.hit)

    def write_batch(
        self, puts: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()
    ) -> None:
        from .crashpoints import crash_point

        crash_point("kv.write_batch.pre")
        payload = _encode_batch(list(puts), list(deletes))
        self._visit_torn_sites(payload)
        with self._lock:
            if self._lib.lsm_write_batch(self._h, payload, len(payload)) != 0:
                raise IOError("LSM write_batch failed")
        # no .mid point: the batch commits inside one native call — the
        # torn-WAL windows are the lsm.wal.* sites above
        crash_point("kv.write_batch.post")

    def write_batch_async(
        self, puts: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()
    ) -> int:
        """Enqueue an atomic batch onto the WAL writer thread WITHOUT
        waiting for its fsync; returns the WAL seq as the barrier ticket.
        The streamed trie commit pipelines through this: chunk N+1's
        Python-side encode overlaps chunk N's write()+fsync(). A crash
        before the barrier can leave these batches durable but unacked —
        callers must only stream data that is SAFE to persist early
        (content-addressed trie nodes: orphans without a root record,
        fsck-clean, shrink reclaims them).

        Deliberately NOT a crash_point/torn-site surface: the generic
        kv.write_batch.* sites use traversal counts as matrix coordinates,
        and streamed chunks would shift every existing hit number. The
        mid-stream window has its own dedicated point
        (trie.merkle.subtree_streamed) in StateManager."""
        payload = _encode_batch(list(puts), list(deletes))
        with self._lock:
            seq = self._lib.lsm_write_batch_async(
                self._h, payload, len(payload)
            )
        if seq == 0:
            raise IOError("LSM write_batch_async failed")
        return int(seq)

    def write_barrier(self, ticket) -> None:
        """Block until the ticketed async batch's WAL record is fsynced."""
        if not ticket:
            return
        with self._lock:
            if self._lib.lsm_write_barrier(self._h, int(ticket)) != 0:
                raise IOError("LSM write_barrier failed")

    def scan_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        blen = ctypes.c_size_t(0)
        if (
            self._lib.lsm_scan_prefix(
                self._h, prefix, len(prefix),
                ctypes.byref(buf), ctypes.byref(blen),
            )
            != 0
        ):
            raise IOError("LSM scan failed")
        try:
            data = ctypes.string_at(buf, blen.value)
        finally:
            self._lib.lsm_free(buf)
        off = 4
        count = int.from_bytes(data[0:4], "little")
        for _ in range(count):
            klen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            k = data[off : off + klen]
            off += klen
            vlen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            v = data[off : off + vlen]
            off += vlen
            yield (k, v)

    def scan_from(
        self, prefix: bytes, after: bytes, limit: int
    ) -> List[Tuple[bytes, bytes]]:
        """Bounded native cursor page (the fast-sync snapshot primitive):
        the engine seeks its SSTable cursors and memtable skiplists to
        prefix+after and merges forward for `limit` live rows — O(seek +
        page) instead of the O(keyspace) full-prefix materialization the
        KVStore default pays via scan_prefix. Row identity with the
        default/SqliteKV pager is test-locked (tests/test_lsm.py)."""
        if limit <= 0:
            return []
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        blen = ctypes.c_size_t(0)
        if (
            self._lib.lsm_scan_from(
                self._h, prefix, len(prefix), after, len(after),
                limit, ctypes.byref(buf), ctypes.byref(blen),
            )
            != 0
        ):
            raise IOError("LSM scan_from failed")
        try:
            data = ctypes.string_at(buf, blen.value)
        finally:
            self._lib.lsm_free(buf)
        out: List[Tuple[bytes, bytes]] = []
        off = 4
        for _ in range(int.from_bytes(data[0:4], "little")):
            klen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            k = data[off : off + klen]
            off += klen
            vlen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            out.append((k, data[off : off + vlen]))
            off += vlen
        return out

    def flush(self) -> None:
        """Seal the memtable and wait until it is a durable sorted table."""
        with self._lock:
            if self._lib.lsm_flush(self._h) != 0:
                raise IOError("LSM flush failed")

    def ingest(
        self, puts: List[Tuple[bytes, bytes]], chunk: int = 2000
    ) -> None:
        """Bulk-load (snapshot shipping / db import): batched writes, then
        seal the memtable so the imported keyspace is durable sorted
        tables — the verification read pass that follows (root walk,
        fsck) hits bloom-filtered SSTs instead of a giant memtable."""
        super().ingest(puts, chunk)
        if puts:
            self.flush()

    def compact(self) -> None:
        """Flush, then run one full merge to a single table (CLI/db verb)."""
        with self._lock:
            if self._lib.lsm_compact_now(self._h) != 0:
                raise IOError("LSM compaction failed")

    def wait_compaction(self) -> None:
        """Block until no background compaction is scheduled or running."""
        self._lib.lsm_wait_compaction(self._h)

    def table_count(self) -> int:
        return int(self._lib.lsm_table_count(self._h))

    def stats(self) -> Dict[str, int]:
        """Engine counters snapshot; publishes the read-path gauges
        (lsm_bloom_hits/misses, lsm_cache_hit_ratio, ...) as a side
        effect so an RPC metrics scrape after a commit sees them."""
        arr = (ctypes.c_uint64 * len(_STAT_FIELDS))()
        self._lib.lsm_stats(self._h, arr, len(_STAT_FIELDS))
        out = dict(zip(_STAT_FIELDS, (int(v) for v in arr)))
        self._publish_metrics(out)
        return out

    @staticmethod
    def _publish_metrics(stats: Dict[str, int]) -> None:
        from ..utils import metrics

        metrics.set_gauge("lsm_bloom_hits", stats["bloom_hits"])
        metrics.set_gauge("lsm_bloom_misses", stats["bloom_misses"])
        lookups = stats["cache_hits"] + stats["cache_misses"]
        metrics.set_gauge(
            "lsm_cache_hit_ratio",
            stats["cache_hits"] / lookups if lookups else 0.0,
        )
        metrics.set_gauge("lsm_table_count", stats["table_count"])
        metrics.set_gauge("lsm_compactions_total", stats["compactions"])
        metrics.set_gauge("lsm_wal_fsyncs_total", stats["wal_fsyncs"])
        metrics.set_gauge("lsm_wal_records_total", stats["wal_records"])
        # sustained non-zero backlog with compactions flat = starved compactor
        metrics.set_gauge("lsm_compaction_backlog", stats["compact_backlog"])

    def close(self) -> None:
        from ..utils import tracing

        # pull buffered engine events (and the fsync/group-commit histogram
        # samples they carry) into the merged tracer before the ring dies
        try:
            tracing.drain_native()
        except Exception:
            pass
        tracing.unregister_native_source(self._trace_source)
        with self._lock:
            if self._h:
                self._lib.lsm_close(self._h)
                self._h = None
