"""LsmKV — the native LSM storage engine behind the KVStore seam.

Role of the reference's RocksDB context
(/root/reference/src/Lachain.Storage/RocksDbContext.cs:23-60): a log-
structured KV store with WAL-synced atomic batches. The engine itself is
C++ (storage/native/lsm.cpp): CRC-framed fsynced WAL -> sorted memtable ->
immutable sorted tables + manifest, full compaction. Durability contract
matches SqliteKV's synchronous=FULL batches (same kill -9 guarantees,
tests/test_lsm.py + test_storage_crash shape).

Single-op put/delete are WAL-synced one-op batches — same semantics as
SqliteKV's autocommit puts, with the fsync cost that implies; bulk paths
use write_batch exactly as they do over SqliteKV.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional, Tuple

from .kv import KVStore

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libllsm.so")
_lib_cache: list = [None]


def _load_lib():
    if _lib_cache[0] is not None:
        return _lib_cache[0]
    sources = [
        os.path.join(_NATIVE_DIR, "lsm.cpp"),
        os.path.join(_NATIVE_DIR, "Makefile"),
    ]
    if not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(_LIB_PATH) < os.path.getmtime(s) for s in sources
    ):
        subprocess.run(
            ["make", "-s", "-C", _NATIVE_DIR], check=True, capture_output=True
        )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.lsm_open.restype = ctypes.c_void_p
    lib.lsm_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.lsm_close.argtypes = [ctypes.c_void_p]
    lib.lsm_write_batch.restype = ctypes.c_int
    lib.lsm_write_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.lsm_get.restype = ctypes.c_int
    lib.lsm_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.lsm_scan_prefix.restype = ctypes.c_int
    lib.lsm_scan_prefix.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.lsm_flush.restype = ctypes.c_int
    lib.lsm_flush.argtypes = [ctypes.c_void_p]
    lib.lsm_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
    lib.lsm_table_count.restype = ctypes.c_uint64
    lib.lsm_table_count.argtypes = [ctypes.c_void_p]
    lib.lsm_version.restype = ctypes.c_int
    assert lib.lsm_version() == 1
    _lib_cache[0] = lib
    return lib


def _encode_batch(
    puts: List[Tuple[bytes, bytes]], deletes: List[bytes]
) -> bytes:
    parts = [(len(puts) + len(deletes)).to_bytes(4, "little")]
    for k, v in puts:
        parts.append(
            b"\x00" + len(k).to_bytes(4, "little") + k
            + len(v).to_bytes(4, "little") + v
        )
    for k in deletes:
        parts.append(
            b"\x01" + len(k).to_bytes(4, "little") + k + b"\x00\x00\x00\x00"
        )
    return b"".join(parts)


class LsmKV(KVStore):
    """Durable KV on the native LSM engine (drop-in for SqliteKV)."""

    def __init__(self, path: str, flush_threshold: int = 8 << 20):
        self._lib = _load_lib()
        self._lock = threading.Lock()
        self._h = self._lib.lsm_open(path.encode(), flush_threshold)
        if not self._h:
            raise IOError(f"cannot open LSM store at {path!r}")

    def get(self, key: bytes) -> Optional[bytes]:
        val = ctypes.POINTER(ctypes.c_ubyte)()
        vlen = ctypes.c_size_t(0)
        r = self._lib.lsm_get(
            self._h, key, len(key), ctypes.byref(val), ctypes.byref(vlen)
        )
        if r < 0:
            raise IOError(f"LSM read failed for key {key!r}")
        if r != 1:
            return None
        try:
            return ctypes.string_at(val, vlen.value)
        finally:
            self._lib.lsm_free(val)

    def put(self, key: bytes, value: bytes) -> None:
        self.write_batch([(key, value)])

    def delete(self, key: bytes) -> None:
        self.write_batch([], [key])

    def write_batch(
        self, puts: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()
    ) -> None:
        from .crashpoints import crash_point

        crash_point("kv.write_batch.pre")
        payload = _encode_batch(list(puts), list(deletes))
        with self._lock:
            if self._lib.lsm_write_batch(self._h, payload, len(payload)) != 0:
                raise IOError("LSM write_batch failed")
        # no .mid point: the batch commits inside one native call — the
        # torn-WAL-tail window is exercised by the engine's own crash test
        crash_point("kv.write_batch.post")

    def scan_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        blen = ctypes.c_size_t(0)
        if (
            self._lib.lsm_scan_prefix(
                self._h, prefix, len(prefix),
                ctypes.byref(buf), ctypes.byref(blen),
            )
            != 0
        ):
            raise IOError("LSM scan failed")
        try:
            data = ctypes.string_at(buf, blen.value)
        finally:
            self._lib.lsm_free(buf)
        off = 4
        count = int.from_bytes(data[0:4], "little")
        for _ in range(count):
            klen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            k = data[off : off + klen]
            off += klen
            vlen = int.from_bytes(data[off : off + 4], "little")
            off += 4
            v = data[off : off + vlen]
            off += vlen
            yield (k, v)

    def flush(self) -> None:
        """Force the memtable into a durable sorted table."""
        with self._lock:
            if self._lib.lsm_flush(self._h) != 0:
                raise IOError("LSM flush failed")

    def table_count(self) -> int:
        return int(self._lib.lsm_table_count(self._h))

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.lsm_close(self._h)
                self._h = None
