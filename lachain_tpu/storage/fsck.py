"""On-open invariant scanner: detect torn states, repair or refuse.

Every commit pipeline in the node is a multi-write sequence, and a crash
(power loss, kill -9, injected crash point) can land between the writes.
The KV's atomic batches bound the damage to a small set of enumerable torn
states; this scanner checks each invariant on open, REPAIRS what is safely
repairable, and REFUSES to let the node start otherwise — a node must
never silently run on inconsistent state.

Invariants (the crash-point matrix in storage/crashpoints.py maps each to
the pipeline window that can violate it):

  tip-roots      the committed tip (BLOCK_HEIGHT) has a snapshot-index row
                 and its StateRoots decode                         [refuse]
  tip-block      the tip height resolves to a stored block         [refuse]
  root-nodes     every tree root at the tip exists as a trie node; --deep
                 walks the full DFS of every retained snapshot     [refuse]
  orphan-block   block entries above the tip (block.persist.mid crash:
                 block batch durable, state commit not) — deleted; the
                 era re-finalizes it deterministically             [repair]
  journal-stale  journal entries for eras already settled on-chain
                 (missed GC) — pruned                              [repair]
  journal-decode undecodable journal values — dropped              [repair]
  pool-decode    undecodable pool entries — dropped                [repair]
  shrink-marks   SHRINK_MARK rows without a SHRINK_STATE — dropped [repair]
  shrink-resume  SHRINK_STATE present: an interrupted shrink will
                 resume on its next run                            [note]

Quick mode (the on-open default) costs a handful of point reads: only one
torn block is possible per crash through the persist pipeline, so orphan
probing checks heights tip+1..tip+PROBE directly instead of scanning the
block index; deep mode (CLI ``fsck --deep``) does the full scans and the
full trie DFS.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.serialization import Reader, write_u64
from .kv import EntryPrefix, KVStore, prefixed
from .state import StateRoots
from .trie import EMPTY_ROOT, InternalNode, _decode as _decode_node

logger = logging.getLogger(__name__)

# quick-mode orphan probe depth above the tip; the persist pipeline can
# leave at most ONE torn block, the margin covers manual tampering
ORPHAN_PROBE = 8

NOTE = "note"
REPAIRED = "repaired"
FATAL = "fatal"


@dataclass
class FsckIssue:
    code: str
    detail: str
    severity: str  # NOTE | REPAIRED | FATAL
    repair: Optional[str] = None  # what the repair did (severity REPAIRED)


@dataclass
class FsckReport:
    issues: List[FsckIssue] = field(default_factory=list)
    checked: List[str] = field(default_factory=list)
    deep: bool = False

    @property
    def clean(self) -> bool:
        return not self.issues

    @property
    def fatal(self) -> bool:
        return any(i.severity == FATAL for i in self.issues)

    @property
    def repaired(self) -> List[FsckIssue]:
        return [i for i in self.issues if i.severity == REPAIRED]

    def to_dict(self) -> dict:
        return {
            "clean": self.clean,
            "fatal": self.fatal,
            "deep": self.deep,
            "checked": list(self.checked),
            "issues": [
                {
                    "code": i.code,
                    "severity": i.severity,
                    "detail": i.detail,
                    **({"repair": i.repair} if i.repair else {}),
                }
                for i in self.issues
            ],
        }


class FsckError(Exception):
    """Raised by the node's open path when fsck refuses the database."""

    def __init__(self, report: FsckReport):
        self.report = report
        fatal = [i for i in report.issues if i.severity == FATAL]
        super().__init__(
            "fsck refused database: "
            + "; ".join(f"[{i.code}] {i.detail}" for i in fatal)
        )


def _tip(kv: KVStore) -> Optional[int]:
    enc = kv.get(prefixed(EntryPrefix.BLOCK_HEIGHT))
    return Reader(enc).u64() if enc else None


def _delete_orphan_block(kv: KVStore, height: int, report: FsckReport) -> None:
    """Remove every trace of a torn block above the tip. Safe by the
    protocol's own guarantee: the era that produced it will re-finalize the
    identical block after restart (deterministic execution over agreed
    txs), and its own tx/index rows must not shadow that replay."""
    hh_key = prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT, write_u64(height))
    h = kv.get(hh_key)
    deletes = [hh_key, prefixed(EntryPrefix.BLOCK_BLOOM, write_u64(height))]
    if h is not None:
        deletes.append(prefixed(EntryPrefix.BLOCK_BY_HASH, h))
        enc = kv.get(prefixed(EntryPrefix.BLOCK_BY_HASH, h))
        if enc is not None:
            try:
                from ..core.types import Block

                block = Block.decode(enc)
                for th in block.tx_hashes:
                    deletes.append(
                        prefixed(EntryPrefix.TRANSACTION_BY_HASH, th)
                    )
            except Exception:
                pass  # the block rows themselves still go
    # address-index rows for the height (prefix scan bounded by the u64
    # height segment living mid-key is not possible — drop via full scan
    # only in deep mode; quick mode leaves unreferenced index rows, which
    # read paths tolerate: they resolve through TRANSACTION_BY_HASH)
    kv.write_batch([], deletes)
    report.issues.append(
        FsckIssue(
            code="orphan-block",
            severity=REPAIRED,
            detail=f"block at height {height} above committed tip",
            repair=f"deleted {len(deletes)} block/tx rows; era will "
            "re-finalize deterministically",
        )
    )


def fsck(
    kv: KVStore, repair: bool = True, deep: bool = False
) -> FsckReport:
    """Scan the database's cross-keyspace invariants. With `repair`,
    safely-repairable issues are fixed in place (severity REPAIRED);
    without it they are reported FATAL so a read-only caller still sees
    them. Unrepairable states are always FATAL — callers must refuse to
    run (FsckError)."""
    report = FsckReport(deep=deep)
    repairable = REPAIRED if repair else FATAL

    tip = _tip(kv)
    report.checked.append("tip-roots")
    roots = None
    if tip is not None:
        enc = kv.get(
            prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(tip))
        )
        if enc is None:
            report.issues.append(
                FsckIssue(
                    code="tip-roots",
                    severity=FATAL,
                    detail=f"committed tip {tip} has no snapshot-index row "
                    "(state roots lost)",
                )
            )
        else:
            try:
                roots = StateRoots.decode(enc)
            except Exception:
                report.issues.append(
                    FsckIssue(
                        code="tip-roots",
                        severity=FATAL,
                        detail=f"snapshot-index row at tip {tip} does not "
                        "decode",
                    )
                )

    report.checked.append("tip-block")
    if tip is not None:
        h = kv.get(
            prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT, write_u64(tip))
        )
        if h is None or kv.get(prefixed(EntryPrefix.BLOCK_BY_HASH, h)) is None:
            report.issues.append(
                FsckIssue(
                    code="tip-block",
                    severity=FATAL,
                    detail=f"committed tip {tip} has state roots but no "
                    "stored block",
                )
            )

    # root-nodes: quick = the tip's tree roots resolve to stored trie
    # nodes; deep = DFS every retained snapshot's full node graph
    report.checked.append("root-nodes")
    if roots is not None:
        if deep:
            heights = []
            idx_prefix = prefixed(EntryPrefix.SNAPSHOT_INDEX)
            for key, _ in kv.scan_prefix(idx_prefix):
                heights.append(int.from_bytes(key[len(idx_prefix):], "big"))
            missing = _deep_trie_check(kv, sorted(heights))
            for h_hex, height in missing:
                report.issues.append(
                    FsckIssue(
                        code="root-nodes",
                        severity=FATAL,
                        detail=f"trie node {h_hex} unreachable for "
                        f"snapshot {height}",
                    )
                )
        else:
            for r in roots.all_roots():
                if r == EMPTY_ROOT:
                    continue
                if kv.get(prefixed(EntryPrefix.TRIE_NODE, r)) is None:
                    report.issues.append(
                        FsckIssue(
                            code="root-nodes",
                            severity=FATAL,
                            detail=f"tip {tip} root {r.hex()} has no "
                            "trie node (trie torn)",
                        )
                    )

    # orphan blocks above the tip (block.persist.mid window)
    report.checked.append("orphan-block")
    base = -1 if tip is None else tip
    if deep:
        hh_prefix = prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT)
        orphans = [
            int.from_bytes(key[len(hh_prefix):], "big")
            for key, _ in kv.scan_prefix(hh_prefix)
            if int.from_bytes(key[len(hh_prefix):], "big") > base
        ]
    else:
        orphans = [
            h
            for h in range(base + 1, base + 1 + ORPHAN_PROBE)
            if kv.get(
                prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT, write_u64(h))
            )
            is not None
        ]
    for height in sorted(orphans):
        if repair:
            _delete_orphan_block(kv, height, report)
        else:
            report.issues.append(
                FsckIssue(
                    code="orphan-block",
                    severity=FATAL,
                    detail=f"block at height {height} above committed tip "
                    f"{tip}",
                )
            )

    # consensus journal: undecodable values and eras settled on-chain
    report.checked.append("journal")
    j_prefix = prefixed(EntryPrefix.CONSENSUS_STATE)
    bad_keys = []
    stale_keys = []
    cutoff = (tip if tip is not None else -1) + 1  # eras <= tip are settled
    for key, value in kv.scan_prefix(j_prefix):
        tail = key[len(j_prefix):]
        if len(tail) != 16:
            bad_keys.append(key)
            continue
        try:
            r = Reader(value)
            r.i64()
            r.bytes_()
        except Exception:
            bad_keys.append(key)
            continue
        if int.from_bytes(tail[:8], "big") < cutoff:
            stale_keys.append(key)
    if bad_keys:
        if repair:
            kv.write_batch([], bad_keys)
        report.issues.append(
            FsckIssue(
                code="journal-decode",
                severity=repairable,
                detail=f"{len(bad_keys)} undecodable journal entries",
                repair="dropped" if repair else None,
            )
        )
    if stale_keys:
        if repair:
            kv.write_batch([], stale_keys)
        report.issues.append(
            FsckIssue(
                code="journal-stale",
                severity=repairable,
                detail=f"{len(stale_keys)} journal entries for eras already "
                f"settled (< {cutoff})",
                repair="pruned" if repair else None,
            )
        )

    # Byzantine evidence records (consensus/evidence.py): malformed keys or
    # undecodable values are repairable garbage — an accusation that cannot
    # be decoded cannot be served and must not wedge la_getEvidence
    report.checked.append("evidence")
    from ..consensus.evidence import EvidenceRecord

    ev_prefix = prefixed(EntryPrefix.EVIDENCE)
    bad_ev = []
    for key, value in kv.scan_prefix(ev_prefix):
        if len(key) != len(ev_prefix) + 8:
            bad_ev.append(key)
            continue
        try:
            EvidenceRecord.decode(value)
        except Exception:
            bad_ev.append(key)
    if bad_ev:
        if repair:
            kv.write_batch([], bad_ev)
        report.issues.append(
            FsckIssue(
                code="evidence-decode",
                severity=repairable,
                detail=f"{len(bad_ev)} undecodable evidence records",
                repair="dropped" if repair else None,
            )
        )

    # pool repository: undecodable entries
    report.checked.append("pool")
    from ..core.types import SignedTransaction

    bad_pool = []
    p_prefix = prefixed(EntryPrefix.POOL_TX)
    for key, value in kv.scan_prefix(p_prefix):
        try:
            SignedTransaction.decode(value)
        except Exception:
            bad_pool.append(key)
    if bad_pool:
        if repair:
            kv.write_batch([], bad_pool)
        report.issues.append(
            FsckIssue(
                code="pool-decode",
                severity=repairable,
                detail=f"{len(bad_pool)} undecodable pool entries",
                repair="dropped" if repair else None,
            )
        )

    # fast-sync frontier spill rows: only meaningful DURING a sync; any
    # row present at open time is leftover from a sync that died mid-
    # download. The download itself is resumable by construction (present
    # trie nodes are skipped), so the rows are pure garbage.
    report.checked.append("fastsync-frontier")
    frontier_keys = [
        key
        for key, _ in kv.scan_prefix(prefixed(EntryPrefix.FASTSYNC_FRONTIER))
    ]
    if frontier_keys:
        if repair:
            kv.write_batch([], frontier_keys)
        report.issues.append(
            FsckIssue(
                code="fastsync-frontier",
                severity=repairable,
                detail=f"{len(frontier_keys)} frontier spill rows from an "
                "interrupted fast sync",
                repair="dropped; a restarted sync rediscovers the frontier"
                if repair
                else None,
            )
        )

    # shrink bookkeeping
    report.checked.append("shrink")
    shrink_state = kv.get(prefixed(EntryPrefix.SHRINK_STATE))
    if shrink_state is not None:
        report.issues.append(
            FsckIssue(
                code="shrink-resume",
                severity=NOTE,
                detail="interrupted shrink pass; resumes on next shrink run",
            )
        )
    else:
        mark_keys = [
            key for key, _ in kv.scan_prefix(prefixed(EntryPrefix.SHRINK_MARK))
        ]
        if mark_keys:
            if repair:
                kv.write_batch([], mark_keys)
            report.issues.append(
                FsckIssue(
                    code="shrink-marks",
                    severity=repairable,
                    detail=f"{len(mark_keys)} mark rows without an active "
                    "shrink pass",
                    repair="dropped" if repair else None,
                )
            )

    if report.fatal:
        logger.error("fsck: REFUSING database: %s", report.to_dict())
    elif not report.clean:
        logger.warning("fsck: repaired/notes: %s", report.to_dict())
    return report


def verify_imported_state(
    kv: KVStore, expect_state_hash: Optional[bytes]
) -> Optional[str]:
    """Migration/snapshot contract check for `db import`: the imported
    store's TIP state roots must hash to `expect_state_hash` (the value
    the operator read from a trusted block header), and the tip trie must
    be fully present. Returns None when the store passes, else a
    human-readable refusal reason. A dump is NOT self-certifying — only
    the operator-supplied expectation ties it to the real chain."""
    tip = _tip(kv)
    if tip is None:
        return "imported store has no committed tip height"
    enc = kv.get(prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(tip)))
    if enc is None:
        return f"imported store has no state roots at tip {tip}"
    try:
        roots = StateRoots.decode(enc)
    except Exception:
        return f"imported state roots at tip {tip} do not decode"
    if expect_state_hash is None:
        return (
            "refusing to trust the dump blindly: pass --expect-root with "
            "the state hash from a trusted block header "
            f"(imported tip {tip} announces {roots.state_hash().hex()})"
        )
    if roots.state_hash() != expect_state_hash:
        return (
            f"imported state root mismatch at tip {tip}: expected "
            f"{expect_state_hash.hex()}, dump contains "
            f"{roots.state_hash().hex()}"
        )
    missing = _deep_trie_check(kv, [tip])
    if missing:
        return (
            f"imported tip {tip} trie is incomplete: "
            f"{len(missing)} unreachable nodes (first {missing[0][0]})"
        )
    return None


def _deep_trie_check(kv: KVStore, heights) -> list:
    """Full DFS from every retained snapshot root; returns
    [(missing_hash_hex, height), ...]. Marks visited hashes so shared
    subtrees cost one walk."""
    missing = []
    seen = set()
    for height in heights:
        enc = kv.get(
            prefixed(EntryPrefix.SNAPSHOT_INDEX, write_u64(height))
        )
        if enc is None:
            continue
        try:
            roots = StateRoots.decode(enc)
        except Exception:
            missing.append(("<roots-undecodable>", height))
            continue
        stack = [r for r in roots.all_roots() if r != EMPTY_ROOT]
        while stack:
            h = stack.pop()
            if h in seen:
                continue
            seen.add(h)
            node_enc = kv.get(prefixed(EntryPrefix.TRIE_NODE, h))
            if node_enc is None:
                missing.append((h.hex(), height))
                continue
            try:
                node = _decode_node(node_enc)
            except Exception:
                missing.append((h.hex(), height))
                continue
            if isinstance(node, InternalNode):
                stack.extend(c for c in node.children if c != EMPTY_ROOT)
    return missing
