"""Per-peer RTT estimation: the clock source for WAN-adaptive recovery.

The block synchronizer already floods `ping_request`/`ping_reply` once a
second to track peer heights (core/synchronizer.py); this module turns that
existing exchange into an RTT instrument. `NetworkManager` stamps the send
time of each ping and feeds the reply latency into an RFC 6298-style
smoothed estimator (SRTT + RTTVAR EWMAs), one per peer.

Consumers scale their fixed timeouts from the observed estimates instead of
reconnect-thrashing distant-but-healthy peers:

  * the node watchdog stretches its stall ladder (`Node._protocol_watchdog`)
    so strike escalation on a 200 ms-RTT link does not fire on a schedule
    tuned for loopback;
  * the block synchronizer widens its per-request timeout to the serving
    peer's RTO;
  * `NetworkManager.reconnect_peers` rations strike-3 forced reconnects
    through a per-peer token bucket refilled on an RTT-scaled interval.

Observed RTTs include send-worker batching delay (flush interval, backoff)
on both sides by construction — that is the latency consensus traffic
actually experiences, which is exactly the number recovery should adapt to.

Clock discipline: all reads are `time.monotonic()` (injectable for tests);
this module is listed under the repo determinism lint's rule D scope
(tools/check_invariants.py DETERMINISTIC_FILES) so wall-clock reads can
never creep in.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from ..utils import metrics

# RFC 6298 smoothing gains
ALPHA = 0.125  # SRTT gain
BETA = 0.25    # RTTVAR gain

# bound the metrics label space (utils/metrics caps label sets per family;
# a gossip-discovered peer flood must not evict the validator gauges)
MAX_TRACKED_PEERS = 128


class PeerRtt:
    """One peer's smoothed estimate."""

    __slots__ = ("srtt", "rttvar", "samples", "last_sent")

    def __init__(self) -> None:
        self.srtt: Optional[float] = None
        self.rttvar: float = 0.0
        self.samples: int = 0
        self.last_sent: Optional[float] = None

    def observe(self, sample: float) -> None:
        if self.srtt is None:
            self.srtt = sample
            self.rttvar = sample / 2.0
        else:
            self.rttvar = (1 - BETA) * self.rttvar + BETA * abs(
                self.srtt - sample
            )
            self.srtt = (1 - ALPHA) * self.srtt + ALPHA * sample
        self.samples += 1


class RttTracker:
    """Per-peer SRTT/RTTVAR over the ping_request/ping_reply exchange.

    Pairing is last-sent: with one outstanding ping per peer per second and
    sub-second RTTs this is exact; when pings overlap, the estimate biases
    low by at most one ping interval — acceptable for timeout scaling,
    which only needs the order of magnitude."""

    def __init__(self, *, clock=time.monotonic) -> None:
        self._clock = clock
        self._peers: Dict[bytes, PeerRtt] = {}

    def _peer(self, peer: bytes) -> Optional[PeerRtt]:
        ent = self._peers.get(peer)
        if ent is None:
            if len(self._peers) >= MAX_TRACKED_PEERS:
                return None
            ent = self._peers[peer] = PeerRtt()
        return ent

    # -- measurement hooks (NetworkManager) ---------------------------------

    def note_sent(self, peer: bytes, now: Optional[float] = None) -> None:
        """A ping_request was enqueued toward `peer`."""
        ent = self._peer(peer)
        if ent is not None:
            ent.last_sent = self._clock() if now is None else now

    def note_reply(
        self, peer: bytes, now: Optional[float] = None
    ) -> Optional[float]:
        """A ping_reply arrived from `peer`; returns the RTT sample taken,
        None when no send was stamped (unsolicited or overflow peer)."""
        ent = self._peers.get(peer)
        if ent is None or ent.last_sent is None:
            return None
        t = self._clock() if now is None else now
        sample = t - ent.last_sent
        ent.last_sent = None
        if sample < 0:
            return None
        ent.observe(sample)
        metrics.set_gauge(
            "network_peer_rtt_ms",
            round(sample * 1000.0, 3),
            labels={"peer": peer[:4].hex()},
        )
        metrics.set_gauge(
            "network_rtt_max_ms", round(self.max_srtt() * 1000.0, 3)
        )
        return sample

    # -- estimates ----------------------------------------------------------

    def srtt(self, peer: bytes) -> Optional[float]:
        ent = self._peers.get(peer)
        return ent.srtt if ent is not None else None

    def rto(
        self, peer: bytes, *, floor: float = 0.2, cap: float = 30.0
    ) -> float:
        """RFC 6298 retransmission timeout: SRTT + 4*RTTVAR, clamped to
        [floor, cap]. An unmeasured peer gets the floor — unknown peers must
        not inflate timeouts."""
        ent = self._peers.get(peer)
        if ent is None or ent.srtt is None:
            return floor
        return min(cap, max(floor, ent.srtt + 4.0 * ent.rttvar))

    def max_srtt(self) -> float:
        """The slowest measured peer's SRTT (0.0 with no samples) — the
        fleet-wide pessimistic bound timeout scaling keys off: graceful
        degradation must hold for the farthest region, not the median."""
        vals = [e.srtt for e in self._peers.values() if e.srtt is not None]
        return max(vals) if vals else 0.0

    def scale(
        self, base: float, *, mult: float = 20.0, cap_mult: float = 4.0
    ) -> float:
        """An RTT-adaptive timeout: `base` on fast links, stretched toward
        `mult * max_srtt` as links get slower, never past `cap_mult * base`
        (adaptivity widens patience, it must not disable the watchdog)."""
        return min(cap_mult * base, max(base, mult * self.max_srtt()))

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-peer estimate table for health/era reports (peer key = first
        4 pubkey bytes, the fleet-trace node naming convention)."""
        out: Dict[str, Dict[str, float]] = {}
        for peer, ent in self._peers.items():
            if ent.srtt is None:
                continue
            out[peer[:4].hex()] = {
                "srtt_ms": round(ent.srtt * 1000.0, 3),
                "rttvar_ms": round(ent.rttvar * 1000.0, 3),
                "samples": ent.samples,
            }
        return out
