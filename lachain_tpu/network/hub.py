"""TCP transport hub: the CommunicationHub equivalent.

Parity with the reference's Go CommunicationHub + HubConnector
(/root/reference/src/Lachain.Networking/Hub/HubConnector.cs:26-105): the
node hands the hub signed `MessageBatch` blobs addressed to a peer public
key; the hub owns sockets, framing, dialing, and redelivery. The reference
relays through external hub nodes; here peers connect directly over
TCP/DCN (consensus traffic is control-plane KB-scale — ICI collectives are
not a transport, SURVEY.md §5).

Framing: 4-byte big-endian length + raw batch bytes.
"""
from __future__ import annotations

import asyncio
import inspect
import logging
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from ..utils import metrics, tracing

logger = logging.getLogger(__name__)

MAX_FRAME = 1 << 26  # 64 MiB

# inbound frame sizes (bytes): worker batches cap at 64 KiB, sync replies
# and fast-sync chunks run far larger
_FRAME_BUCKETS = (
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576, 8388608,
)


def _accepts_conn_id(cb: Callable) -> bool:
    """True when `cb` can take the (data, conn_id) pair. Decided ONCE at
    construction — a per-frame try/except TypeError would also swallow
    genuine TypeErrors raised inside the handler."""
    try:
        sig = inspect.signature(cb)
    except (TypeError, ValueError):
        return True  # uninspectable (C callable): assume the full contract
    n_positional = 0
    for p in sig.parameters.values():
        if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        ):
            n_positional += 1
        elif p.kind is inspect.Parameter.VAR_POSITIONAL:
            return True
    return n_positional >= 2


@dataclass(frozen=True)
class PeerAddress:
    public_key: bytes  # 33-byte compressed ECDSA key (identity)
    host: str
    port: int


class Hub:
    """Owns the listening socket and outbound connections."""

    def __init__(
        self,
        host: str,
        port: int,
        on_batch: Callable[..., None],
        frame_filter=None,
    ):
        self.host = host
        self.port = port
        # injectable fault filter (network/faults.py TcpFrameFilter): decides
        # per-frame drop/delay/duplication so a seeded FaultPlan reproduces
        # a failure over real sockets. None = deliver everything.
        self.frame_filter = frame_filter
        self._fault_tasks: set = set()
        # called as on_batch(data, conn_id) when the callable accepts two
        # positional args, else on_batch(data) — conn_id identifies the
        # INBOUND connection the batch arrived on, for reverse delivery to
        # peers that cannot be dialed (NAT'd relay clients). Arity is
        # resolved once here so a 1-arg handler receives traffic instead
        # of raising TypeError on every frame.
        self.on_batch = on_batch
        self._pass_conn_id = _accepts_conn_id(on_batch)
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: Dict[Tuple[str, int], asyncio.StreamWriter] = {}
        self._conn_locks: Dict[Tuple[str, int], asyncio.Lock] = {}
        self._reader_tasks: set = set()
        self._inbound: Dict[int, asyncio.StreamWriter] = {}
        self._next_conn_id = 1

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_inbound, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]  # resolve port 0 -> actual

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for t in list(self._fault_tasks):
            t.cancel()
        self._fault_tasks.clear()
        # cancel inbound readers first: wait_closed() (3.12+) blocks until
        # every connection handler returns
        for t in list(self._reader_tasks):
            t.cancel()
        if self._reader_tasks:
            await asyncio.gather(*self._reader_tasks, return_exceptions=True)
        for w in list(self._conns.values()):
            w.close()
        self._conns.clear()
        if self._server is not None:
            await self._server.wait_closed()

    async def _read_frames(self, reader, conn_id) -> None:
        """Shared frame loop for both directions (batches are
        connection-agnostic; identity lives in the batch signature)."""
        while True:
            # the inter-frame gap IS this node's network receive wait:
            # tag it so the era report's idle decomposition can claim it
            with tracing.wait("net", conn=conn_id):
                header = await reader.readexactly(4)
            n = int.from_bytes(header, "big")
            if n > MAX_FRAME:
                raise ValueError("oversized frame")
            data = await reader.readexactly(n)
            metrics.observe_hist(
                "network_frame_bytes", n, buckets=_FRAME_BUCKETS
            )
            if self.frame_filter is not None and not self.frame_filter.inbound(
                data
            ):
                continue  # injected inbound suppression (crashed self)
            try:
                if self._pass_conn_id:
                    self.on_batch(data, conn_id)
                else:
                    self.on_batch(data)
            except Exception:
                logger.exception("batch handler failed")

    async def _handle_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._reader_tasks.add(task)
        conn_id = self._next_conn_id
        self._next_conn_id += 1
        self._inbound[conn_id] = writer
        try:
            await self._read_frames(reader, conn_id)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._inbound.pop(conn_id, None)
            writer.close()
            if task is not None:
                self._reader_tasks.discard(task)

    def _schedule_faulted(self, delay: float, send) -> None:
        """Run coroutine-factory `send` after `delay` (fault-injected
        latency); tracked so stop() cancels in-flight delayed frames."""

        async def later():
            await asyncio.sleep(delay)
            await send()

        t = asyncio.get_running_loop().create_task(later())
        self._fault_tasks.add(t)
        t.add_done_callback(self._fault_tasks.discard)

    async def _send_filtered(self, peer, data: bytes, send) -> bool:
        """Apply the frame filter to one outbound frame. `send` is an async
        thunk performing the real write. A dropped frame reports SUCCESS:
        injected loss must look like the network ate it, so repair can only
        come from the message-request/outbox-replay layer — a False here
        would let the worker's own requeue path mask the fault."""
        plan = self.frame_filter.outbound(peer, data)
        if not plan:
            return True
        ok = True
        sent_now = False
        for delay in plan:
            if delay > 0:
                self._schedule_faulted(delay, send)
            else:
                sent_now = True
                ok = await send() and ok
        return ok if sent_now else True

    async def send_on_conn(self, conn_id: int, data: bytes) -> bool:
        """Reverse delivery over a live INBOUND connection (the only path
        to a NAT'd peer: it dialed us, we answer on its socket)."""
        if self.frame_filter is not None:
            return await self._send_filtered(
                None, data, lambda: self._send_on_conn_now(conn_id, data)
            )
        return await self._send_on_conn_now(conn_id, data)

    async def _send_on_conn_now(self, conn_id: int, data: bytes) -> bool:
        writer = self._inbound.get(conn_id)
        if writer is None:
            return False
        try:
            writer.write(len(data).to_bytes(4, "big") + data)
            await writer.drain()
            return True
        except (ConnectionError, OSError):
            self._inbound.pop(conn_id, None)
            writer.close()
            return False

    async def _read_outbound(self, reader, key, my_writer) -> None:
        """Outbound connections are READ too: a relay answers a NAT'd
        node over the very connection the node dialed out (reverse
        delivery) — frames arriving there are ordinary batches."""
        try:
            await self._read_frames(reader, None)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError,
                asyncio.CancelledError):
            pass
        finally:
            # close ONLY the connection this reader belongs to: a stale
            # reader waking after a re-dial must not kill the replacement
            my_writer.close()
            if self._conns.get(key) is my_writer:
                self._conns.pop(key, None)

    async def send_raw(self, peer: PeerAddress, data: bytes) -> bool:
        """Send one framed batch; dials on demand, drops the cached
        connection on failure (next send re-dials)."""
        if self.frame_filter is not None:
            return await self._send_filtered(
                peer, data, lambda: self._send_raw_now(peer, data)
            )
        return await self._send_raw_now(peer, data)

    async def _send_raw_now(self, peer: PeerAddress, data: bytes) -> bool:
        key = (peer.host, peer.port)
        lock = self._conn_locks.setdefault(key, asyncio.Lock())
        async with lock:
            writer = self._conns.get(key)
            for attempt in (0, 1):
                if writer is None:
                    try:
                        reader, writer = await asyncio.open_connection(
                            peer.host, peer.port
                        )
                        self._conns[key] = writer
                        t = asyncio.get_running_loop().create_task(
                            self._read_outbound(reader, key, writer)
                        )
                        self._reader_tasks.add(t)
                        t.add_done_callback(self._reader_tasks.discard)
                    except OSError:
                        return False
                try:
                    writer.write(len(data).to_bytes(4, "big") + data)
                    await writer.drain()
                    return True
                except (ConnectionError, OSError):
                    writer.close()
                    self._conns.pop(key, None)
                    writer = None
            return False
