"""Per-peer send worker: priority queue + size/time batching.

Parity with the reference's ClientWorker
(/root/reference/src/Lachain.Networking/Hub/ClientWorker.cs:38-143): one
worker per peer, an interval-heap priority queue, batches capped at 64 KiB
flushed at ~4 Hz — but as an asyncio task instead of a thread.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import List, Optional

from .hub import Hub, PeerAddress
from .wire import MessageBatch, MessageFactory, NetworkMessage, PRIORITY

MAX_BATCH_BYTES = 64 * 1024
FLUSH_INTERVAL = 0.25


class ClientWorker:
    def __init__(
        self,
        peer: PeerAddress,
        factory: MessageFactory,
        hub: Hub,
        *,
        flush_interval: float = FLUSH_INTERVAL,
        max_batch_bytes: int = MAX_BATCH_BYTES,
    ):
        self.peer = peer
        self._factory = factory
        self._hub = hub
        self._flush_interval = flush_interval
        self._max_batch_bytes = max_batch_bytes
        self._heap: List = []
        self._seq = itertools.count()
        self._wakeup = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        self._wakeup.set()
        if self._task is not None:
            await self._task

    def enqueue(self, msg: NetworkMessage) -> None:
        heapq.heappush(
            self._heap, (PRIORITY[msg.kind], next(self._seq), msg)
        )
        # wake immediately once a batch's worth is pending
        pending = sum(len(m.body) + 6 for _, _, m in self._heap)
        if pending >= self._max_batch_bytes:
            self._wakeup.set()

    def _drain_batch(self) -> List[NetworkMessage]:
        out: List[NetworkMessage] = []
        size = 0
        while self._heap and size < self._max_batch_bytes:
            _, _, msg = heapq.heappop(self._heap)
            out.append(msg)
            size += len(msg.body) + 6
        return out

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(
                    self._wakeup.wait(), timeout=self._flush_interval
                )
            except asyncio.TimeoutError:
                pass
            self._wakeup.clear()
            while self._heap:
                msgs = self._drain_batch()
                batch: MessageBatch = self._factory.batch(msgs)
                ok = await self._hub.send_raw(self.peer, batch.encode())
                if not ok:
                    # peer unreachable: requeue and back off; consensus
                    # retransmission is handled at the protocol layer
                    for m in msgs:
                        heapq.heappush(
                            self._heap,
                            (PRIORITY[m.kind], next(self._seq), m),
                        )
                    await asyncio.sleep(self._flush_interval)
                    break
        # final flush on stop
        if self._heap:
            msgs = self._drain_batch()
            await self._hub.send_raw(self.peer, self._factory.batch(msgs).encode())
