"""Per-peer send worker: priority queue + size/time batching.

Parity with the reference's ClientWorker
(/root/reference/src/Lachain.Networking/Hub/ClientWorker.cs:38-143): one
worker per peer, an interval-heap priority queue, batches capped at 64 KiB
flushed at ~4 Hz — but as an asyncio task instead of a thread.
"""
from __future__ import annotations

import asyncio
import random
import zlib
from collections import deque
from typing import List, Optional

from ..utils import metrics
from .hub import Hub, PeerAddress
from .wire import MessageBatch, MessageFactory, NetworkMessage, PRIORITY

MAX_BATCH_BYTES = 64 * 1024
FLUSH_INTERVAL = 0.25
# bound on bytes a dead peer's queue may hold before low-priority traffic
# is shed (reconnect storms must not OOM the node); consensus messages are
# the highest priority so they shed last
MAX_QUEUE_BYTES = 8 * 1024 * 1024
BACKOFF_MAX = 8.0


class ClientWorker:
    def __init__(
        self,
        peer: PeerAddress,
        factory: MessageFactory,
        hub: Hub,
        *,
        flush_interval: float = FLUSH_INTERVAL,
        max_batch_bytes: int = MAX_BATCH_BYTES,
        transport=None,
    ):
        self.peer = peer
        self._factory = factory
        self._hub = hub
        # transport(peer, batch_bytes) -> bool; default dials the peer
        # directly. Relay-routed peers get a transport that wraps the
        # signed batch in a relay_forward envelope instead (the envelope
        # preserves end-to-end authentication — the inner batch carries
        # OUR signature and only the target verifies it).
        self._transport = transport or (
            lambda p, data: self._hub.send_raw(p, data)
        )
        self._flush_interval = flush_interval
        self._max_batch_bytes = max_batch_bytes
        # one FIFO deque per priority level (PRIORITY values are a small
        # fixed set): O(1) enqueue, O(1) priority-ordered drain, O(1) shed
        # from the least-important tail — a heap paid O(n) scans per
        # message once a dead peer's queue hit the cap
        self._queues = {p: deque() for p in sorted(set(PRIORITY.values()))}
        self._wakeup = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._queued_bytes = 0
        self._backoff = flush_interval
        # WAN hint (manager/rtt): redial pacing should start near the
        # link's actual RTT — on a 300 ms link a flush-interval-paced
        # first retry burns a dial that cannot have completed yet
        self.backoff_floor = 0.0
        self.consecutive_failures = 0
        # ±25% reconnect jitter, seeded per (us, peer) pair: deterministic
        # for replay, yet different across peers — after a relay blip every
        # worker fleet-wide would otherwise redial in lockstep at exactly
        # backoff*2^k and re-stampede the returning host
        jitter_seed = zlib.crc32(
            factory.public_key
            + (peer.public_key if peer is not None else b"")
        )
        self._jitter = random.Random(jitter_seed)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        self._wakeup.set()
        if self._task is not None:
            await self._task

    def _pending(self) -> bool:
        return any(self._queues.values())

    def reset_backoff(self) -> None:
        """Stall-escalation hook: the peer is believed back — retry NOW
        (the queued/undelivered buffer drains on the first successful
        flush) instead of sleeping out the current backoff window."""
        self._backoff = self._flush_interval
        self._wakeup.set()

    def enqueue(self, msg: NetworkMessage) -> None:
        self._queues[PRIORITY[msg.kind]].append(msg)
        self._queued_bytes += len(msg.body) + 6
        # shed the least-important traffic (numerically largest priority,
        # newest first) when a dead peer's queue passes the cap; consensus
        # outlives pool gossip
        while self._queued_bytes > MAX_QUEUE_BYTES:
            victim = None
            for p in sorted(self._queues, reverse=True):
                if self._queues[p]:
                    victim = self._queues[p].pop()
                    break
            if victim is None:
                break
            self._queued_bytes -= len(victim.body) + 6
            # shedding must be visible: a fast-sync serving peer whose
            # client went away sheds multi-MB snapshot/trie replies here,
            # and a silent drop looks identical to a wire bug
            metrics.inc(
                "network_worker_shed_total",
                labels={"priority": str(PRIORITY[victim.kind])},
            )
        # wake immediately once a batch's worth is pending
        if self._queued_bytes >= self._max_batch_bytes:
            self._wakeup.set()

    def _drain_batch(self) -> List[NetworkMessage]:
        out: List[NetworkMessage] = []
        size = 0
        for p in sorted(self._queues):
            q = self._queues[p]
            while q and size < self._max_batch_bytes:
                msg = q.popleft()
                out.append(msg)
                size += len(msg.body) + 6
            if size >= self._max_batch_bytes:
                break
        self._queued_bytes = max(0, self._queued_bytes - size)
        return out

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(
                    self._wakeup.wait(), timeout=self._flush_interval
                )
            except asyncio.TimeoutError:
                pass
            self._wakeup.clear()
            while self._pending():
                msgs = self._drain_batch()
                batch: MessageBatch = self._factory.batch(msgs)
                ok = await self._transport(self.peer, batch.encode())
                if ok:
                    self._backoff = self._flush_interval
                    self.consecutive_failures = 0
                else:
                    # peer unreachable: requeue and back off EXPONENTIALLY
                    # (a down peer must not be re-dialed 4x/s forever);
                    # every send_raw re-dials, so recovery is the first
                    # successful dial after the peer returns
                    self.consecutive_failures += 1
                    metrics.inc("network_reconnect_attempts_total")
                    for m in reversed(msgs):
                        # requeue at the FRONT of each priority queue so
                        # ordering within a priority is preserved
                        self._queues[PRIORITY[m.kind]].appendleft(m)
                        self._queued_bytes += len(m.body) + 6
                    pause = max(self._backoff, self.backoff_floor)
                    await asyncio.sleep(
                        pause * (0.75 + 0.5 * self._jitter.random())
                    )
                    self._backoff = min(pause * 2, BACKOFF_MAX)
                    break
        # final flush on stop
        if self._pending():
            msgs = self._drain_batch()
            await self._transport(
                self.peer, self._factory.batch(msgs).encode()
            )
