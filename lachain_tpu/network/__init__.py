"""Networking layer: signed message batches over a TCP hub.

Parity with /root/reference/src/Lachain.Networking (SURVEY.md §2f):
wire.py = MessageBatch/MessageFactory + NetworkMessage oneof;
hub.py = CommunicationHub equivalent; worker.py = ClientWorker;
manager.py = NetworkManagerBase.
"""
from .hub import Hub, PeerAddress
from .manager import NetworkManager
from .wire import MessageBatch, MessageFactory, NetworkMessage

__all__ = [
    "Hub",
    "PeerAddress",
    "NetworkManager",
    "MessageBatch",
    "MessageFactory",
    "NetworkMessage",
]
