"""Network wire format: consensus payload codec + message kinds + batches.

Parity with the reference's proto layer
(/root/reference/src/Lachain.Proto/networking.proto — `NetworkMessage` oneof
of 7 kinds, `MessageBatch{sender, signature, content}`;
consensus.proto:77-91 — `ConsensusMessage` oneof of 9 payloads) using the
framework's fixed-width codec instead of protobuf.

A `MessageBatch` is the unit of transport: sender's compressed message list,
ECDSA-signed (reference MessageFactory.cs:80-103, verified at
NetworkManagerBase.cs:117-122; Deflate compression per HubConnector.cs:98).
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..consensus import messages as M
from ..core.types import Block, SignedTransaction
from ..crypto import ecdsa
from ..crypto.hashes import keccak256
from ..utils.serialization import (
    Reader,
    write_bytes,
    write_bytes_list,
    write_i64,
    write_u32,
    write_u64,
)

# ---------------------------------------------------------------------------
# consensus payload codec (the ConsensusMessage oneof)
# ---------------------------------------------------------------------------

_VAL, _ECHO, _READY, _BVAL, _AUX, _CONF, _COIN, _DEC, _HDR = range(1, 10)


def _enc_rbc(rbc: M.ReliableBroadcastId) -> bytes:
    return write_i64(rbc.era) + write_u32(rbc.sender_id)


def _dec_rbc(r: Reader) -> M.ReliableBroadcastId:
    return M.ReliableBroadcastId(era=r.i64(), sender_id=r.u32())


def _enc_bb(bb: M.BinaryBroadcastId) -> bytes:
    return write_i64(bb.era) + write_i64(bb.agreement) + write_i64(bb.epoch)


def _dec_bb(r: Reader) -> M.BinaryBroadcastId:
    return M.BinaryBroadcastId(era=r.i64(), agreement=r.i64(), epoch=r.i64())


def encode_payload(p) -> bytes:
    if isinstance(p, M.ValMessage):
        return (
            bytes([_VAL])
            + _enc_rbc(p.rbc)
            + write_bytes(p.root)
            + write_bytes_list(list(p.branch))
            + write_bytes(p.shard)
            + write_u32(p.shard_index)
        )
    if isinstance(p, M.EchoMessage):
        return (
            bytes([_ECHO])
            + _enc_rbc(p.rbc)
            + write_bytes(p.root)
            + write_bytes_list(list(p.branch))
            + write_bytes(p.shard)
            + write_u32(p.shard_index)
        )
    if isinstance(p, M.ReadyMessage):
        return bytes([_READY]) + _enc_rbc(p.rbc) + write_bytes(p.root)
    if isinstance(p, M.BValMessage):
        return bytes([_BVAL]) + _enc_bb(p.bb) + bytes([1 if p.value else 0])
    if isinstance(p, M.AuxMessage):
        return bytes([_AUX]) + _enc_bb(p.bb) + bytes([1 if p.value else 0])
    if isinstance(p, M.ConfMessage):
        mask = (1 if False in p.values else 0) | (2 if True in p.values else 0)
        return bytes([_CONF]) + _enc_bb(p.bb) + bytes([mask])
    if isinstance(p, M.CoinMessage):
        c = p.coin
        return (
            bytes([_COIN])
            + write_i64(c.era)
            + write_i64(c.agreement)
            + write_i64(c.epoch)
            + write_bytes(p.share)
        )
    if isinstance(p, M.DecryptedMessage):
        return (
            bytes([_DEC])
            + write_i64(p.hb.era)
            + write_u32(p.share_id)
            + write_bytes(p.payload)
        )
    if isinstance(p, M.SignedHeaderMessage):
        return (
            bytes([_HDR])
            + write_i64(p.root.era)
            + write_bytes(p.header_bytes)
            + write_bytes(p.signature)
        )
    raise TypeError(f"unencodable payload {type(p)}")


def decode_payload(data: bytes):
    r = Reader(data)
    tag = r.raw(1)[0]
    if tag in (_VAL, _ECHO):
        rbc = _dec_rbc(r)
        root = r.bytes_()
        branch = tuple(r.bytes_list())
        shard = r.bytes_()
        idx = r.u32()
        cls = M.ValMessage if tag == _VAL else M.EchoMessage
        return cls(rbc=rbc, root=root, branch=branch, shard=shard, shard_index=idx)
    if tag == _READY:
        return M.ReadyMessage(rbc=_dec_rbc(r), root=r.bytes_())
    if tag == _BVAL:
        return M.BValMessage(bb=_dec_bb(r), value=r.raw(1)[0] != 0)
    if tag == _AUX:
        return M.AuxMessage(bb=_dec_bb(r), value=r.raw(1)[0] != 0)
    if tag == _CONF:
        bb = _dec_bb(r)
        mask = r.raw(1)[0]
        vals = frozenset(
            v for v, bit in ((False, 1), (True, 2)) if mask & bit
        )
        return M.ConfMessage(bb=bb, values=vals)
    if tag == _COIN:
        coin = M.CoinId(era=r.i64(), agreement=r.i64(), epoch=r.i64())
        return M.CoinMessage(coin=coin, share=r.bytes_())
    if tag == _DEC:
        hb = M.HoneyBadgerId(era=r.i64())
        return M.DecryptedMessage(hb=hb, share_id=r.u32(), payload=r.bytes_())
    if tag == _HDR:
        root = M.RootProtocolId(era=r.i64())
        return M.SignedHeaderMessage(
            root=root, header_bytes=r.bytes_(), signature=r.bytes_()
        )
    raise ValueError(f"unknown payload tag {tag}")


# ---------------------------------------------------------------------------
# network messages (the NetworkMessage oneof) + priorities
# ---------------------------------------------------------------------------

KIND_CONSENSUS = 1
KIND_PING_REQUEST = 2
KIND_PING_REPLY = 3
KIND_SYNC_BLOCKS_REQUEST = 4
KIND_SYNC_BLOCKS_REPLY = 5
KIND_SYNC_POOL_REQUEST = 6
KIND_SYNC_POOL_REPLY = 7
KIND_FAST_SYNC_REQUEST = 8
KIND_FAST_SYNC_REPLY = 9
KIND_TRIE_NODES_REQUEST = 10
KIND_TRIE_NODES_REPLY = 11
KIND_PEERS_REQUEST = 12
KIND_PEERS_REPLY = 13
# relay/NAT traversal (role of the reference's hub-relay network,
# Hub/HubConnector.cs:26-105): a node with no dialable address registers
# with a public relay and receives traffic wrapped in relay_forward
# messages, delivered back over its own outbound TCP connection
KIND_RELAY_REGISTER = 14
KIND_RELAY_FORWARD = 15
# consensus retransmission (role of the reference node's message-request/
# resend layer): HBBFT protocols never retransmit, so a node missing
# messages for an era re-requests them; the receiver replays its per-era
# outbox (consensus/era.py) addressed to the requester
KIND_MESSAGE_REQUEST = 16
# request-id variants of the trie-node exchange (reference
# RequestManager.cs: every batch carries a request id so late/duplicate
# replies can never be attributed to the wrong in-flight batch). The
# id-less kinds 10/11 stay served for older peers; new clients only
# send 17 and consume 18.
KIND_TRIE_NODES_REQUEST_ID = 17
KIND_TRIE_NODES_REPLY_ID = 18
# snapshot shipping: cursor-paged pull of a peer's raw trie-node rows
# (the bulk alternative to node-by-node download; the db export/import
# dump format reframed as a wire exchange). Pull-based paging keeps the
# receiver in control: one page in flight per request id, resumable at
# the cursor from a different peer mid-stream.
KIND_SNAPSHOT_REQUEST = 19
KIND_SNAPSHOT_REPLY = 20

# reference NetworkMessagePriority: replies < consensus < pool sync
PRIORITY = {
    KIND_PING_REPLY: 0,
    KIND_SYNC_BLOCKS_REPLY: 0,
    KIND_SYNC_POOL_REPLY: 0,
    KIND_FAST_SYNC_REQUEST: 2,
    KIND_FAST_SYNC_REPLY: 0,
    KIND_TRIE_NODES_REQUEST: 2,
    KIND_TRIE_NODES_REPLY: 0,
    KIND_TRIE_NODES_REQUEST_ID: 2,
    KIND_TRIE_NODES_REPLY_ID: 0,
    KIND_SNAPSHOT_REQUEST: 2,
    KIND_SNAPSHOT_REPLY: 0,
    KIND_CONSENSUS: 1,
    KIND_PING_REQUEST: 2,
    KIND_SYNC_BLOCKS_REQUEST: 2,
    KIND_SYNC_POOL_REQUEST: 2,
    KIND_PEERS_REQUEST: 2,
    KIND_PEERS_REPLY: 2,
    KIND_RELAY_REGISTER: 1,
    KIND_RELAY_FORWARD: 1,  # carries consensus traffic: consensus priority
    KIND_MESSAGE_REQUEST: 1,  # unblocks consensus: consensus priority
}


@dataclass(frozen=True)
class NetworkMessage:
    kind: int
    body: bytes  # kind-specific encoding

    def encode(self) -> bytes:
        return bytes([self.kind]) + write_bytes(self.body)

    @classmethod
    def decode_from(cls, r: Reader) -> "NetworkMessage":
        kind = r.raw(1)[0]
        if kind not in PRIORITY:
            raise ValueError(f"unknown message kind {kind}")
        return cls(kind=kind, body=r.bytes_())


def consensus_msg(era: int, payload) -> NetworkMessage:
    return NetworkMessage(
        KIND_CONSENSUS, write_i64(era) + encode_payload(payload)
    )


def parse_consensus(msg: NetworkMessage) -> Tuple[int, object]:
    r = Reader(msg.body)
    era = r.i64()
    return era, decode_payload(r.rest())


def message_request(era: int) -> NetworkMessage:
    """Ask a peer to replay its consensus outbox for `era` to us — the
    recovery path for a wedged era (a lost RBC ECHO is unrecoverable for
    its slot without retransmission). Replays are rate-limited per
    (peer, era) on the serving side."""
    return NetworkMessage(KIND_MESSAGE_REQUEST, write_i64(era))


def parse_message_request(msg: NetworkMessage) -> int:
    r = Reader(msg.body)
    era = r.i64()
    r.assert_eof()
    return era


def ping_request(height: int) -> NetworkMessage:
    return NetworkMessage(KIND_PING_REQUEST, write_u64(height))


def ping_reply(height: int) -> NetworkMessage:
    return NetworkMessage(KIND_PING_REPLY, write_u64(height))


def parse_height(msg: NetworkMessage) -> int:
    return Reader(msg.body).u64()


def sync_blocks_request(start: int, count: int) -> NetworkMessage:
    return NetworkMessage(
        KIND_SYNC_BLOCKS_REQUEST, write_u64(start) + write_u32(count)
    )


def parse_sync_blocks_request(msg: NetworkMessage) -> Tuple[int, int]:
    r = Reader(msg.body)
    return r.u64(), r.u32()


def sync_blocks_reply(blocks: List[Tuple[Block, List[SignedTransaction]]]) -> NetworkMessage:
    out = write_u32(len(blocks))
    for block, txs in blocks:
        out += write_bytes(block.encode())
        out += write_bytes_list([t.encode() for t in txs])
    return NetworkMessage(KIND_SYNC_BLOCKS_REPLY, out)


def parse_sync_blocks_reply(
    msg: NetworkMessage,
) -> List[Tuple[Block, List[SignedTransaction]]]:
    r = Reader(msg.body)
    out = []
    for _ in range(r.u32()):
        block = Block.decode(r.bytes_())
        txs = [SignedTransaction.decode(t) for t in r.bytes_list()]
        out.append((block, txs))
    return out


def sync_pool_request(hashes: List[bytes]) -> NetworkMessage:
    return NetworkMessage(KIND_SYNC_POOL_REQUEST, write_bytes_list(hashes))


def parse_sync_pool_request(msg: NetworkMessage) -> List[bytes]:
    return Reader(msg.body).bytes_list()


def sync_pool_reply(txs: List[SignedTransaction]) -> NetworkMessage:
    return NetworkMessage(
        KIND_SYNC_POOL_REPLY, write_bytes_list([t.encode() for t in txs])
    )


def parse_sync_pool_reply(msg: NetworkMessage) -> List[SignedTransaction]:
    return [SignedTransaction.decode(t) for t in Reader(msg.body).bytes_list()]


# ---------------------------------------------------------------------------
# signed batches
# ---------------------------------------------------------------------------

# Trace-context trailer (fleet observability): a fixed-width suffix INSIDE
# `content`, appended AFTER the zlib stream ends. Placement is the whole
# design: `messages()` decompresses with a decompressobj, which stops at
# the stream end and leaves trailing bytes in `unused_data` — so a
# trailer-free decoder (any pre-trailer build) accepts the frame
# unchanged, and the batch signature (over the full content bytes) covers
# the trailer for free. DESIGN DIVERGENCE from a trailer "past the signed
# region": appending after the signature'd field would trip the old
# decoder's assert_eof and break mixed-version interop — inside-content
# placement is the variant old peers actually tolerate, and an
# authenticated trace context is strictly better than an unauthenticated
# one. Layout (29 bytes):
#   magic "LTRC" (4) | version 0x01 (1) | origin (8) | era i64 (8) |
#   trace id (8)
# origin = keccak256(sender pubkey)[:8]; trace id =
# era_trace_id(sender, era) — both deterministic, so the fleet merger can
# recompute them from the era report alone and match receiver-side
# wire.trace_ctx instants without any coordination.
TRACE_TRAILER_MAGIC = b"LTRC"
TRACE_TRAILER_VERSION = 1
TRACE_TRAILER_LEN = 4 + 1 + 8 + 8 + 8

# Wire/engine version handshake (rolling upgrades): the LTRC trick,
# generalized. A second fixed-width block rides in the same
# ignored-by-old-decoders tail region of `content`, BEFORE the trace
# trailer (the trailer must stay the outermost suffix: legacy
# `trace_trailer()` parses the last 29 bytes unconditionally, so any block
# appended after it would break trace parsing on un-upgraded peers).
# Tail layout, outermost last:
#   <zlib stream> [LTRX handshake, 13 bytes] [LTRC trailer, 29 bytes]
# Handshake layout (13 bytes):
#   magic "LTRX" (4) | hs version 0x01 (1) | wire_version u16 |
#   engine_version u16 | feature bits u32
# Signed for free (batch signature covers content), invisible to
# pre-handshake decoders, and piggybacked on every batch — no extra
# round-trip, and a restarted peer's version is re-learned on its first
# frame.
HANDSHAKE_MAGIC = b"LTRX"
HANDSHAKE_VERSION = 1
HANDSHAKE_LEN = 4 + 1 + 2 + 2 + 4

# The compatibility matrix. WIRE_VERSION is the frame/kind vocabulary;
# ENGINE_VERSION is the consensus engine generation (informational — mixed
# engines are expected mid-upgrade and never gate traffic). The contract
# that makes node-by-node rolling upgrades safe is ADJACENCY: version v
# interoperates with v±1, so a fleet may straddle two consecutive wire
# versions during a roll but never three. Skipping a wire version requires
# two rolls.
WIRE_VERSION = 2  # v1 = pre-handshake (implicit); v2 adds LTRX + snapshots
ENGINE_VERSION = 1
MIN_COMPAT_WIRE_VERSION = 1

# feature bits (advertised capabilities, not gates)
FEATURE_TRACE_TRAILER = 1 << 0
FEATURE_SNAPSHOT_SYNC = 1 << 1
FEATURES_DEFAULT = FEATURE_TRACE_TRAILER | FEATURE_SNAPSHOT_SYNC

# Minimum wire version that understands each kind. Kinds absent from a
# peer's vocabulary raise in its decode_from — so a sender must not emit
# them toward a peer that has ADVERTISED an older version. Peers that have
# never advertised (legacy, pre-handshake) are assumed version 1.
KIND_MIN_WIRE = {k: 1 for k in PRIORITY}
KIND_MIN_WIRE[KIND_SNAPSHOT_REQUEST] = 2
KIND_MIN_WIRE[KIND_SNAPSHOT_REPLY] = 2


def compatible(a: int, b: int) -> bool:
    """True iff wire versions `a` and `b` may share a link (adjacency
    contract: |a-b| <= 1)."""
    return abs(a - b) <= 1


@dataclass(frozen=True)
class WireHandshake:
    """A peer's advertised versions, parsed off its batch tail."""

    wire_version: int
    engine_version: int
    features: int

    def encode(self) -> bytes:
        return (
            HANDSHAKE_MAGIC
            + bytes([HANDSHAKE_VERSION])
            + self.wire_version.to_bytes(2, "big")
            + self.engine_version.to_bytes(2, "big")
            + self.features.to_bytes(4, "big")
        )

    @classmethod
    def decode(cls, raw: bytes) -> Optional["WireHandshake"]:
        if (
            len(raw) != HANDSHAKE_LEN
            or raw[:4] != HANDSHAKE_MAGIC
            or raw[4] != HANDSHAKE_VERSION
        ):
            return None
        return cls(
            wire_version=int.from_bytes(raw[5:7], "big"),
            engine_version=int.from_bytes(raw[7:9], "big"),
            features=int.from_bytes(raw[9:13], "big"),
        )


def node_trace_origin(pub: bytes) -> bytes:
    """8-byte node lane id for the fleet trace (stable per pubkey)."""
    return keccak256(pub)[:8]


def era_trace_id(pub: bytes, era: int) -> bytes:
    """The 8-byte trace id a node attaches to its era-`era` consensus
    traffic. A pure function of (sender, era): every observer derives the
    identical id, so cross-node causality needs no id exchange."""
    return keccak256(pub + write_i64(era))[:8]


@dataclass(frozen=True)
class MessageBatch:
    sender: bytes  # 33-byte compressed ECDSA pubkey
    signature: bytes  # 65-byte recoverable sig over keccak(content)
    content: bytes  # zlib-compressed encoded message list

    def encode(self) -> bytes:
        return (
            write_bytes(self.sender)
            + write_bytes(self.signature)
            + write_bytes(self.content)
        )

    @classmethod
    def decode(cls, data: bytes) -> "MessageBatch":
        r = Reader(data)
        sender = r.bytes_()
        sig = r.bytes_()
        content = r.bytes_()
        r.assert_eof()
        return cls(sender, sig, content)

    def verify(self) -> bool:
        return ecdsa.verify_hash(
            self.sender, keccak256(self.content), self.signature
        )

    def messages(self) -> List[NetworkMessage]:
        # decompress with a hard output cap: zlib.decompress's bufsize is only
        # an initial buffer size, so a small compressed frame could otherwise
        # expand to tens of GB before any size check runs (zip-bomb)
        d = zlib.decompressobj()
        raw = d.decompress(self.content, 1 << 26)
        if d.unconsumed_tail or not d.eof:
            raise ValueError("batch too large")
        # bytes past the zlib stream end land in d.unused_data and are
        # IGNORED here by design: that tail is where the optional trace
        # trailer rides (trace_trailer()), and ignoring unknown tails is
        # what makes the trailer forward-compatible
        r = Reader(raw)
        out = []
        for _ in range(r.u32()):
            out.append(NetworkMessage.decode_from(r))
        r.assert_eof()
        return out

    def trace_trailer(self) -> Optional[Tuple[bytes, int, bytes]]:
        """Parse the optional trace-context trailer: (origin, era,
        trace_id), or None when absent. O(1) — reads the content SUFFIX
        without decompressing, so the receive hot path pays a 5-byte
        compare per batch. A zlib stream coincidentally ending in the
        magic+version bytes (2^-40) would yield a garbage-but-harmless
        trace context; the trailer is observability-only and never feeds
        consensus."""
        c = self.content
        if len(c) < TRACE_TRAILER_LEN:
            return None
        tail = c[len(c) - TRACE_TRAILER_LEN:]
        if (
            tail[:4] != TRACE_TRAILER_MAGIC
            or tail[4] != TRACE_TRAILER_VERSION
        ):
            return None
        origin = tail[5:13]
        era = int.from_bytes(tail[13:21], "big", signed=True)
        return origin, era, tail[21:29]

    def handshake(self) -> Optional[WireHandshake]:
        """Parse the optional version-handshake block, or None when absent.
        O(1) suffix reads, like trace_trailer(): the block sits either at
        the very end of content (no trailer on this batch) or immediately
        before the 29-byte trace trailer."""
        c = self.content
        for off in (len(c) - HANDSHAKE_LEN,
                    len(c) - HANDSHAKE_LEN - TRACE_TRAILER_LEN):
            if off < 0:
                continue
            hs = WireHandshake.decode(c[off:off + HANDSHAKE_LEN])
            if hs is not None:
                return hs
        return None


class MessageFactory:
    """Builds + signs message batches (reference MessageFactory.cs:13-103)."""

    def __init__(self, ecdsa_priv: bytes):
        self._priv = ecdsa_priv
        self.public_key = ecdsa.public_key_bytes(ecdsa_priv)
        # emit the trace-context trailer on consensus-bearing batches.
        # On by default (the trailer is invisible to trailer-free
        # decoders); tests flip it off to model a pre-trailer sender
        self.trace_trailer = True
        self._origin = node_trace_origin(self.public_key)
        # version handshake: advertised on every batch. Tests and the
        # rolling-upgrade drill flip `handshake` off (or the versions
        # down) to model a legacy / mid-upgrade sender
        self.handshake = True
        self.wire_version = WIRE_VERSION
        self.engine_version = ENGINE_VERSION
        self.features = FEATURES_DEFAULT

    def batch(self, msgs: List[NetworkMessage]) -> MessageBatch:
        raw = write_u32(len(msgs)) + b"".join(m.encode() for m in msgs)
        content = zlib.compress(raw, level=1)
        if self.handshake:
            # before the trace trailer: the trailer must stay the
            # outermost suffix (see tail layout at HANDSHAKE_MAGIC)
            content += WireHandshake(
                wire_version=self.wire_version,
                engine_version=self.engine_version,
                features=self.features,
            ).encode()
        if self.trace_trailer:
            # era = the newest era among the batch's consensus messages
            # (a flush batch can mix eras under pipelining; the receiver's
            # per-era set keeps ids for every era it actually dispatches)
            era = None
            for m in msgs:
                if m.kind == KIND_CONSENSUS and len(m.body) >= 8:
                    e = int.from_bytes(m.body[:8], "big", signed=True)
                    if era is None or e > era:
                        era = e
            if era is not None:
                content += (
                    TRACE_TRAILER_MAGIC
                    + bytes([TRACE_TRAILER_VERSION])
                    + self._origin
                    + write_i64(era)
                    + era_trace_id(self.public_key, era)
                )
        sig = ecdsa.sign_hash(self._priv, keccak256(content))
        return MessageBatch(
            sender=self.public_key, signature=sig, content=content
        )


# -- fast state sync (reference FastSynchronizerBatch / StateDownloader) -----


def fast_sync_request(height: int) -> NetworkMessage:
    """Ask for the block + state roots at `height` (0 = serving peer's tip)."""
    return NetworkMessage(KIND_FAST_SYNC_REQUEST, write_u64(height))


def parse_fast_sync_request(msg: NetworkMessage) -> int:
    return Reader(msg.body).u64()


def fast_sync_reply(block: Optional[Block], roots_enc: bytes) -> NetworkMessage:
    body = write_bytes(block.encode() if block else b"") + write_bytes(roots_enc)
    return NetworkMessage(KIND_FAST_SYNC_REPLY, body)


def parse_fast_sync_reply(msg: NetworkMessage):
    r = Reader(msg.body)
    raw = r.bytes_()
    block = Block.decode(raw) if raw else None
    return block, r.bytes_()


def trie_nodes_request(hashes: List[bytes]) -> NetworkMessage:
    return NetworkMessage(KIND_TRIE_NODES_REQUEST, write_bytes_list(hashes))


def parse_trie_nodes_request(msg: NetworkMessage) -> List[bytes]:
    return Reader(msg.body).bytes_list()


def trie_nodes_reply(nodes: List[bytes]) -> NetworkMessage:
    """Node encodings only: receivers verify content-addressing
    (keccak(node) must equal the requested hash), so replies are
    trustless."""
    return NetworkMessage(KIND_TRIE_NODES_REPLY, write_bytes_list(nodes))


def parse_trie_nodes_reply(msg: NetworkMessage) -> List[bytes]:
    return Reader(msg.body).bytes_list()


def trie_nodes_request_id(request_id: int, hashes: List[bytes]) -> NetworkMessage:
    """Request-id variant: the reply echoes `request_id`, so a late or
    duplicated reply to an abandoned batch is simply dropped by the
    scheduler instead of being consumed as the current batch's answer."""
    return NetworkMessage(
        KIND_TRIE_NODES_REQUEST_ID,
        write_u64(request_id) + write_bytes_list(hashes),
    )


def parse_trie_nodes_request_id(msg: NetworkMessage) -> Tuple[int, List[bytes]]:
    r = Reader(msg.body)
    rid = r.u64()
    hashes = r.bytes_list()
    r.assert_eof()
    return rid, hashes


def trie_nodes_reply_id(request_id: int, nodes: List[bytes]) -> NetworkMessage:
    return NetworkMessage(
        KIND_TRIE_NODES_REPLY_ID,
        write_u64(request_id) + write_bytes_list(nodes),
    )


def parse_trie_nodes_reply_id(msg: NetworkMessage) -> Tuple[int, List[bytes]]:
    r = Reader(msg.body)
    rid = r.u64()
    nodes = r.bytes_list()
    r.assert_eof()
    return rid, nodes


def snapshot_request(request_id: int, cursor: bytes, limit: int) -> NetworkMessage:
    """Ask for one page of the peer's trie-node rows starting AFTER
    `cursor` (b"" = from the beginning), at most `limit` records. The
    cursor is a plain trie-node hash, so a partially shipped snapshot
    resumes from any other peer."""
    return NetworkMessage(
        KIND_SNAPSHOT_REQUEST,
        write_u64(request_id) + write_bytes(cursor) + write_u32(limit),
    )


def parse_snapshot_request(msg: NetworkMessage) -> Tuple[int, bytes, int]:
    r = Reader(msg.body)
    rid = r.u64()
    cursor = r.bytes_()
    limit = r.u32()
    r.assert_eof()
    return rid, cursor, limit


def snapshot_reply(
    request_id: int, next_cursor: bytes, done: bool, records: List[bytes]
) -> NetworkMessage:
    """One page of raw trie-node encodings. Records are self-certifying:
    the importer stores each under keccak(record), so a bogus record can
    waste bandwidth but never poison state (the root walk won't reach it)."""
    body = (
        write_u64(request_id)
        + write_bytes(next_cursor)
        + bytes([1 if done else 0])
        + write_bytes_list(records)
    )
    return NetworkMessage(KIND_SNAPSHOT_REPLY, body)


def parse_snapshot_reply(msg: NetworkMessage) -> Tuple[int, bytes, bool, List[bytes]]:
    r = Reader(msg.body)
    rid = r.u64()
    next_cursor = r.bytes_()
    done = r.raw(1)[0] != 0
    records = r.bytes_list()
    r.assert_eof()
    return rid, next_cursor, done, records


# -- peer discovery (gossip-learned addresses; reference: the hub relay
# network's bootstrap + peer exchange, HubConnector.cs:26-105 +
# config_mainnet.json:22-33 — here peers exchange dialable addresses
# directly) ------------------------------------------------------------------


def peers_request(my_host: str, my_port: int) -> NetworkMessage:
    """Ask a peer for its address book; carries OUR listening address so an
    inbound-only acquaintance becomes dialable."""
    return NetworkMessage(
        KIND_PEERS_REQUEST,
        write_bytes(my_host.encode()) + write_u32(my_port),
    )


def parse_peers_request(msg: NetworkMessage) -> Tuple[str, int]:
    r = Reader(msg.body)
    host = r.bytes_().decode()
    port = r.u32()
    r.assert_eof()
    return host, port


def relay_register() -> NetworkMessage:
    """Sent by a NAT'd node to its relay: hold my registration and deliver
    relay_forward traffic addressed to me over this connection. Re-sent
    periodically (refreshes the TTL and keeps the NAT mapping alive)."""
    return NetworkMessage(KIND_RELAY_REGISTER, b"")


def relay_forward(target_pub: bytes, inner_batch: bytes) -> NetworkMessage:
    """Wrap a SIGNED batch for `target_pub` to be delivered by the relay.
    The inner batch carries the original sender's signature, so the relay
    cannot forge or tamper — it only moves bytes."""
    return NetworkMessage(
        KIND_RELAY_FORWARD, write_bytes(target_pub) + write_bytes(inner_batch)
    )


def parse_relay_forward(msg: NetworkMessage) -> Tuple[bytes, bytes]:
    r = Reader(msg.body)
    target = r.bytes_()
    inner = r.bytes_()
    r.assert_eof()
    return target, inner


# host sentinel in peers books for a peer reachable only through a relay:
# "~" + relay pubkey hex (port is ignored)
RELAY_HOST_PREFIX = "~"


def relay_host(relay_pub: bytes) -> str:
    return RELAY_HOST_PREFIX + relay_pub.hex()


def parse_relay_host(host: str):
    """The relay pubkey from a sentinel host, or None for a normal host."""
    if not host.startswith(RELAY_HOST_PREFIX):
        return None
    try:
        pub = bytes.fromhex(host[1:])
    except ValueError:
        return None
    return pub if len(pub) == 33 else None


def peers_reply(peers: List[Tuple[bytes, str, int]]) -> NetworkMessage:
    body = write_u32(len(peers))
    for pub, host, port in peers:
        body += write_bytes(pub) + write_bytes(host.encode()) + write_u32(port)
    return NetworkMessage(KIND_PEERS_REPLY, body)


def parse_peers_reply(msg: NetworkMessage) -> List[Tuple[bytes, str, int]]:
    r = Reader(msg.body)
    out = []
    for _ in range(r.u32()):
        pub = r.bytes_()
        host = r.bytes_().decode()
        port = r.u32()
        if len(pub) != 33:
            raise ValueError("bad peer pubkey length")
        out.append((pub, host, port))
    r.assert_eof()
    return out
