"""Network manager: peer registry, batch verification, event dispatch.

Parity with the reference's NetworkManagerBase
(/root/reference/src/Lachain.Networking/NetworkManagerBase.cs:96-196): a
worker per peer public key, inbound batches are signature-verified then
fanned out to per-kind event handlers; consensus `send_to` addresses
validators by ECDSA public key (IConsensusMessageDeliverer.SendTo,
NetworkManagerBase.cs:66-69).
"""
from __future__ import annotations

import asyncio
import logging
import zlib
from typing import Callable, Dict, List, Optional

from . import wire
from .hub import Hub, PeerAddress
from .wire import MessageBatch, MessageFactory, NetworkMessage
from .worker import ClientWorker

logger = logging.getLogger(__name__)


class NetworkManager:
    def __init__(
        self,
        ecdsa_priv: bytes,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        flush_interval: float = 0.25,
    ):
        self.factory = MessageFactory(ecdsa_priv)
        self.public_key = self.factory.public_key
        self.hub = Hub(host, port, self._on_raw_batch)
        self._flush_interval = flush_interval
        self._workers: Dict[bytes, ClientWorker] = {}
        # event handlers: fn(sender_pubkey, message)
        self.on_consensus: Optional[Callable[[bytes, int, object], None]] = None
        self.on_ping_request: Optional[Callable[[bytes, int], None]] = None
        self.on_ping_reply: Optional[Callable[[bytes, int], None]] = None
        self.on_sync_blocks_request: Optional[Callable] = None
        self.on_fast_sync_request: Optional[Callable] = None
        self.on_fast_sync_reply: Optional[Callable] = None
        self.on_trie_nodes_request: Optional[Callable] = None
        self.on_trie_nodes_reply: Optional[Callable] = None
        self.on_sync_blocks_reply: Optional[Callable] = None
        self.on_sync_pool_request: Optional[Callable] = None
        self.on_sync_pool_reply: Optional[Callable] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.hub.start()

    async def stop(self) -> None:
        for w in self._workers.values():
            await w.stop()
        await self.hub.stop()

    @property
    def address(self) -> PeerAddress:
        return PeerAddress(self.public_key, self.hub.host, self.hub.port)

    def add_peer(self, peer: PeerAddress) -> None:
        if peer.public_key == self.public_key:
            return
        if peer.public_key in self._workers:
            return
        worker = ClientWorker(
            peer, self.factory, self.hub,
            flush_interval=self._flush_interval,
        )
        self._workers[peer.public_key] = worker
        worker.start()

    @property
    def peers(self) -> List[bytes]:
        return list(self._workers.keys())

    # -- sending -----------------------------------------------------------

    def send_to(self, public_key: bytes, msg: NetworkMessage) -> None:
        worker = self._workers.get(public_key)
        if worker is None:
            logger.warning("no worker for peer %s", public_key.hex()[:16])
            return
        worker.enqueue(msg)

    def broadcast(self, msg: NetworkMessage) -> None:
        for worker in self._workers.values():
            worker.enqueue(msg)

    # -- receiving ---------------------------------------------------------

    def _on_raw_batch(self, data: bytes) -> None:
        try:
            batch = MessageBatch.decode(data)
        except ValueError:
            logger.warning("undecodable batch dropped")
            return
        if not batch.verify():
            logger.warning("batch with bad signature dropped")
            return
        try:
            msgs = batch.messages()
        except (ValueError, zlib.error):
            logger.warning("corrupt batch content dropped")
            return
        for msg in msgs:
            try:
                self._dispatch(batch.sender, msg)
            except Exception:
                logger.exception("message handler failed")

    def _dispatch(self, sender: bytes, msg: NetworkMessage) -> None:
        k = msg.kind
        if k == wire.KIND_CONSENSUS and self.on_consensus:
            era, payload = wire.parse_consensus(msg)
            self.on_consensus(sender, era, payload)
        elif k == wire.KIND_PING_REQUEST and self.on_ping_request:
            self.on_ping_request(sender, wire.parse_height(msg))
        elif k == wire.KIND_PING_REPLY and self.on_ping_reply:
            self.on_ping_reply(sender, wire.parse_height(msg))
        elif k == wire.KIND_SYNC_BLOCKS_REQUEST and self.on_sync_blocks_request:
            start, count = wire.parse_sync_blocks_request(msg)
            self.on_sync_blocks_request(sender, start, count)
        elif k == wire.KIND_SYNC_BLOCKS_REPLY and self.on_sync_blocks_reply:
            self.on_sync_blocks_reply(sender, wire.parse_sync_blocks_reply(msg))
        elif k == wire.KIND_SYNC_POOL_REQUEST and self.on_sync_pool_request:
            self.on_sync_pool_request(sender, wire.parse_sync_pool_request(msg))
        elif k == wire.KIND_SYNC_POOL_REPLY and self.on_sync_pool_reply:
            self.on_sync_pool_reply(sender, wire.parse_sync_pool_reply(msg))
        elif k == wire.KIND_FAST_SYNC_REQUEST and self.on_fast_sync_request:
            self.on_fast_sync_request(sender, wire.parse_fast_sync_request(msg))
        elif k == wire.KIND_FAST_SYNC_REPLY and self.on_fast_sync_reply:
            self.on_fast_sync_reply(sender, *wire.parse_fast_sync_reply(msg))
        elif k == wire.KIND_TRIE_NODES_REQUEST and self.on_trie_nodes_request:
            self.on_trie_nodes_request(sender, wire.parse_trie_nodes_request(msg))
        elif k == wire.KIND_TRIE_NODES_REPLY and self.on_trie_nodes_reply:
            self.on_trie_nodes_reply(sender, wire.parse_trie_nodes_reply(msg))
