"""Network manager: peer registry, batch verification, event dispatch.

Parity with the reference's NetworkManagerBase
(/root/reference/src/Lachain.Networking/NetworkManagerBase.cs:96-196): a
worker per peer public key, inbound batches are signature-verified then
fanned out to per-kind event handlers; consensus `send_to` addresses
validators by ECDSA public key (IConsensusMessageDeliverer.SendTo,
NetworkManagerBase.cs:66-69).
"""
from __future__ import annotations

import asyncio
import logging
import zlib
from typing import Callable, Dict, List, Optional

from . import wire
from .hub import Hub, PeerAddress
from .wire import MessageBatch, MessageFactory, NetworkMessage
from .worker import ClientWorker

logger = logging.getLogger(__name__)


class NetworkManager:
    def __init__(
        self,
        ecdsa_priv: bytes,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        flush_interval: float = 0.25,
        advertise_host: Optional[str] = None,
    ):
        # the address peers should DIAL — differs from the bind host when
        # binding a wildcard (0.0.0.0) or behind NAT in multi-host deploys
        self.advertise_host = advertise_host or host
        self.factory = MessageFactory(ecdsa_priv)
        self.public_key = self.factory.public_key
        self.hub = Hub(host, port, self._on_raw_batch)
        self._flush_interval = flush_interval
        self._workers: Dict[bytes, ClientWorker] = {}
        # sends addressed to peers we have not discovered yet: buffered
        # (bounded per peer) and drained the moment the address is learned —
        # consensus protocols do not retransmit, so a message dropped during
        # the bootstrap/discovery race can wedge an era (a lost RBC ECHO is
        # unrecoverable for the slot)
        self._undelivered: Dict[bytes, List[NetworkMessage]] = {}
        self._undelivered_cap = 2048
        # event handlers: fn(sender_pubkey, message)
        self.on_consensus: Optional[Callable[[bytes, int, object], None]] = None
        self.on_ping_request: Optional[Callable[[bytes, int], None]] = None
        self.on_ping_reply: Optional[Callable[[bytes, int], None]] = None
        self.on_sync_blocks_request: Optional[Callable] = None
        self.on_fast_sync_request: Optional[Callable] = None
        self.on_fast_sync_reply: Optional[Callable] = None
        self.on_trie_nodes_request: Optional[Callable] = None
        self.on_trie_nodes_reply: Optional[Callable] = None
        self.on_sync_blocks_reply: Optional[Callable] = None
        self.on_sync_pool_request: Optional[Callable] = None
        self.on_sync_pool_reply: Optional[Callable] = None
        # gossip peer discovery: fired when a previously-unknown peer is
        # learned from a peers_reply (after the worker already exists)
        self.on_peer_discovered: Optional[Callable[[PeerAddress], None]] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.hub.start()

    async def stop(self) -> None:
        for w in self._workers.values():
            await w.stop()
        await self.hub.stop()

    @property
    def address(self) -> PeerAddress:
        return PeerAddress(self.public_key, self.hub.host, self.hub.port)

    def add_peer(self, peer: PeerAddress, authoritative: bool = True) -> None:
        """Install (or update) the dialing address for a peer.

        `authoritative` addresses come from config or from the peer ITSELF
        (a peers_request rides a signature-verified batch from that pubkey)
        and may REPLACE an existing binding — a restarted peer on a new
        port, or a binding poisoned by bogus gossip, corrects itself the
        moment the real peer makes contact. Third-party gossip
        (peers_reply entries) is non-authoritative: it can only introduce
        UNKNOWN peers, never rebind a known one, so a Byzantine address
        book cannot blackhole traffic to a validator we already reach.
        """
        if peer.public_key == self.public_key:
            return
        old = self._workers.get(peer.public_key)
        if old is not None:
            if not authoritative or (
                old.peer.host == peer.host and old.peer.port == peer.port
            ):
                return
            # self-declared address change: rebind
            logger.info(
                "peer %s rebinds %s:%d -> %s:%d",
                peer.public_key.hex()[:16],
                old.peer.host, old.peer.port, peer.host, peer.port,
            )
            self._workers.pop(peer.public_key, None)
            try:
                asyncio.get_event_loop().create_task(old.stop())
            except RuntimeError:  # no running loop (tests)
                pass
        worker = ClientWorker(
            peer, self.factory, self.hub,
            flush_interval=self._flush_interval,
        )
        self._workers[peer.public_key] = worker
        worker.start()
        # gossip crawl: ask every new acquaintance for its address book,
        # carrying our own dialable address so it can dial back
        # (config-seeded + gossip-learned peers; reference reaches peers
        # through bootstrap relays, HubConnector.cs:26-105 +
        # config_mainnet.json:22-33)
        worker.enqueue(
            wire.peers_request(self.advertise_host, self.hub.port)
        )
        for msg in self._undelivered.pop(peer.public_key, ()):
            worker.enqueue(msg)

    @property
    def peers(self) -> List[bytes]:
        return list(self._workers.keys())

    # -- sending -----------------------------------------------------------

    def send_to(self, public_key: bytes, msg: NetworkMessage) -> None:
        worker = self._workers.get(public_key)
        if worker is None:
            pending = self._undelivered.setdefault(public_key, [])
            if len(pending) < self._undelivered_cap:
                pending.append(msg)
            else:
                logger.warning(
                    "undelivered buffer full for unknown peer %s",
                    public_key.hex()[:16],
                )
            return
        worker.enqueue(msg)

    def broadcast(self, msg: NetworkMessage) -> None:
        for worker in self._workers.values():
            worker.enqueue(msg)

    # -- receiving ---------------------------------------------------------

    def _on_raw_batch(self, data: bytes) -> None:
        try:
            batch = MessageBatch.decode(data)
        except ValueError:
            logger.warning("undecodable batch dropped")
            return
        if not batch.verify():
            logger.warning("batch with bad signature dropped")
            return
        try:
            msgs = batch.messages()
        except (ValueError, zlib.error):
            logger.warning("corrupt batch content dropped")
            return
        for msg in msgs:
            try:
                self._dispatch(batch.sender, msg)
            except Exception:
                logger.exception("message handler failed")

    def _dispatch(self, sender: bytes, msg: NetworkMessage) -> None:
        k = msg.kind
        if k == wire.KIND_CONSENSUS and self.on_consensus:
            era, payload = wire.parse_consensus(msg)
            self.on_consensus(sender, era, payload)
        elif k == wire.KIND_PING_REQUEST and self.on_ping_request:
            self.on_ping_request(sender, wire.parse_height(msg))
        elif k == wire.KIND_PING_REPLY and self.on_ping_reply:
            self.on_ping_reply(sender, wire.parse_height(msg))
        elif k == wire.KIND_SYNC_BLOCKS_REQUEST and self.on_sync_blocks_request:
            start, count = wire.parse_sync_blocks_request(msg)
            self.on_sync_blocks_request(sender, start, count)
        elif k == wire.KIND_SYNC_BLOCKS_REPLY and self.on_sync_blocks_reply:
            self.on_sync_blocks_reply(sender, wire.parse_sync_blocks_reply(msg))
        elif k == wire.KIND_SYNC_POOL_REQUEST and self.on_sync_pool_request:
            self.on_sync_pool_request(sender, wire.parse_sync_pool_request(msg))
        elif k == wire.KIND_SYNC_POOL_REPLY and self.on_sync_pool_reply:
            self.on_sync_pool_reply(sender, wire.parse_sync_pool_reply(msg))
        elif k == wire.KIND_FAST_SYNC_REQUEST and self.on_fast_sync_request:
            self.on_fast_sync_request(sender, wire.parse_fast_sync_request(msg))
        elif k == wire.KIND_FAST_SYNC_REPLY and self.on_fast_sync_reply:
            self.on_fast_sync_reply(sender, *wire.parse_fast_sync_reply(msg))
        elif k == wire.KIND_TRIE_NODES_REQUEST and self.on_trie_nodes_request:
            self.on_trie_nodes_request(sender, wire.parse_trie_nodes_request(msg))
        elif k == wire.KIND_TRIE_NODES_REPLY and self.on_trie_nodes_reply:
            self.on_trie_nodes_reply(sender, wire.parse_trie_nodes_reply(msg))
        elif k == wire.KIND_PEERS_REQUEST:
            self._on_peers_request(sender, msg)
        elif k == wire.KIND_PEERS_REPLY:
            self._on_peers_reply(msg)

    # -- gossip peer discovery ---------------------------------------------

    def _on_peers_request(self, sender: bytes, msg: NetworkMessage) -> None:
        host, port = wire.parse_peers_request(msg)
        # the requester's self-declared address arrived under its own batch
        # signature: authoritative (installs OR rebinds), so an inbound-only
        # acquaintance gets a worker to carry the reply
        self.add_peer(
            PeerAddress(public_key=sender, host=host, port=port),
            authoritative=True,
        )
        book = [
            (w.peer.public_key, w.peer.host, w.peer.port)
            for w in self._workers.values()
            if w.peer.public_key != sender
        ]
        book.append((self.public_key, self.advertise_host, self.hub.port))
        self.send_to(sender, wire.peers_reply(book))

    def _on_peers_reply(self, msg: NetworkMessage) -> None:
        try:
            entries = wire.parse_peers_reply(msg)
        except ValueError:
            logger.warning("malformed peers reply dropped")
            return
        for pub, host, port in entries:
            if pub == self.public_key or pub in self._workers:
                continue
            peer = PeerAddress(public_key=pub, host=host, port=port)
            # third-party gossip: may only INTRODUCE unknown peers
            self.add_peer(peer, authoritative=False)
            if self.on_peer_discovered:
                try:
                    self.on_peer_discovered(peer)
                except Exception:
                    logger.exception("peer-discovered handler failed")
