"""Network manager: peer registry, batch verification, event dispatch.

Parity with the reference's NetworkManagerBase
(/root/reference/src/Lachain.Networking/NetworkManagerBase.cs:96-196): a
worker per peer public key, inbound batches are signature-verified then
fanned out to per-kind event handlers; consensus `send_to` addresses
validators by ECDSA public key (IConsensusMessageDeliverer.SendTo,
NetworkManagerBase.cs:66-69).
"""
from __future__ import annotations

import asyncio
import logging
import zlib
from typing import Callable, Dict, List, Optional

from ..utils import metrics
from . import wire
from .hub import Hub, PeerAddress
from .rtt import RttTracker
from .wire import MessageBatch, MessageFactory, NetworkMessage
from .worker import ClientWorker

logger = logging.getLogger(__name__)


class NetworkManager:
    def __init__(
        self,
        ecdsa_priv: bytes,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        flush_interval: float = 0.25,
        advertise_host: Optional[str] = None,
    ):
        # the address peers should DIAL — differs from the bind host when
        # binding a wildcard (0.0.0.0) or behind NAT in multi-host deploys
        self.advertise_host = advertise_host or host
        self.factory = MessageFactory(ecdsa_priv)
        self.public_key = self.factory.public_key
        self.hub = Hub(host, port, self._on_raw_batch)
        self._flush_interval = flush_interval
        self._workers: Dict[bytes, ClientWorker] = {}
        # sends addressed to peers we have not discovered yet: buffered
        # (bounded per peer) and drained the moment the address is learned —
        # consensus protocols do not retransmit, so a message dropped during
        # the bootstrap/discovery race can wedge an era (a lost RBC ECHO is
        # unrecoverable for the slot)
        self._undelivered: Dict[bytes, List[NetworkMessage]] = {}
        self._undelivered_cap = 2048
        # trace-context trailers observed on verified inbound batches:
        # era -> {trace id hex}. Bounded to the newest _TRACE_ERA_KEEP
        # eras — the fleet merger only correlates recent eras, and a
        # byzantine peer stamping absurd era numbers can at worst cycle
        # this dict, never grow it (ids per era are bounded by peers)
        self.era_trace_ids: Dict[int, set] = {}
        self._TRACE_ERA_KEEP = 8
        # event handlers: fn(sender_pubkey, message)
        self.on_consensus: Optional[Callable[[bytes, int, object], None]] = None
        self.on_ping_request: Optional[Callable[[bytes, int], None]] = None
        self.on_ping_reply: Optional[Callable[[bytes, int], None]] = None
        self.on_sync_blocks_request: Optional[Callable] = None
        self.on_fast_sync_request: Optional[Callable] = None
        self.on_fast_sync_reply: Optional[Callable] = None
        self.on_trie_nodes_request: Optional[Callable] = None
        self.on_trie_nodes_reply: Optional[Callable] = None
        # request-id variants (fn(sender, request_id, ...)) + cursor-paged
        # snapshot shipping — the multi-peer fast-sync exchange
        self.on_trie_nodes_request_id: Optional[Callable] = None
        self.on_trie_nodes_reply_id: Optional[Callable] = None
        self.on_snapshot_request: Optional[Callable] = None
        self.on_snapshot_reply: Optional[Callable] = None
        self.on_sync_blocks_reply: Optional[Callable] = None
        self.on_sync_pool_request: Optional[Callable] = None
        self.on_sync_pool_reply: Optional[Callable] = None
        # consensus retransmission: fn(sender_pubkey, era) — the node
        # answers by replaying its era outbox to the sender
        self.on_message_request: Optional[Callable[[bytes, int], None]] = None
        # gossip peer discovery: fired when a previously-unknown peer is
        # learned from a peers_reply (after the worker already exists)
        self.on_peer_discovered: Optional[Callable[[PeerAddress], None]] = None
        # --- relay / NAT traversal (reference Hub/HubConnector.cs) ---
        # as a RELAY: registered NAT'd clients + the inbound connection
        # each last spoke on (reverse-delivery path)
        self.relay_clients: Dict[bytes, float] = {}   # pub -> last seen
        self._last_conn: Dict[bytes, int] = {}        # pub -> conn id
        self._relay_client_ttl = 90.0
        # as a NAT'D NODE: the relay we registered with (None = direct),
        # plus the configured fallback list for relay HA: when the current
        # relay stops answering, registration fails over down the list
        self._my_relay: Optional[PeerAddress] = None
        self._relays: List[PeerAddress] = []
        self._relay_idx = 0
        self.relay_failover_after = 3  # consecutive send failures
        self._reregister_task = None
        # as a SENDER: peers reachable only through a relay
        self._relay_route: Dict[bytes, bytes] = {}    # peer pub -> relay pub
        # --- WAN adaptivity ---
        # per-peer RTT EWMAs off the ping exchange; timeout scaling for the
        # watchdog / synchronizer / reconnect rationing reads these
        self.rtt = RttTracker()
        # wire/engine versions peers have advertised via the LTRX batch
        # tail. Absent entry = legacy peer (assumed wire v1); gating only
        # ever applies to EXPLICITLY-advertised-older peers, so a fleet of
        # pre-handshake builds behaves exactly as before
        self.peer_versions: Dict[bytes, wire.WireHandshake] = {}
        # strike-3 forced-reconnect rationing: a per-peer token bucket so
        # sustained high RTT cannot reconnect-thrash a slow-but-alive peer
        # every escalation cycle. Refill interval stretches with observed
        # fleet RTT (slower fleet -> scarcer reconnects).
        self.reconnect_bucket_capacity = 2.0
        self.reconnect_min_interval = 30.0
        self._reconnect_buckets: Dict[bytes, List[float]] = {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.hub.start()

    async def stop(self) -> None:
        if self._reregister_task is not None:
            self._reregister_task.cancel()
            self._reregister_task = None
        for w in self._workers.values():
            await w.stop()
        await self.hub.stop()

    # -- relay / NAT traversal ---------------------------------------------

    def use_relay(self, relay, reregister_every: float = 20.0) -> None:
        """NAT'd mode: register with a relay and advertise ourselves as
        reachable through it. The registration re-sends periodically —
        it refreshes the relay's TTL and keeps the NAT mapping warm.

        `relay` is one PeerAddress or a LIST of them (relay HA): the node
        registers with the first and, when that relay's worker accumulates
        `relay_failover_after` consecutive send failures, rotates to the
        next one and re-advertises the new route to every peer (the
        self-declared address in a peers_request is authoritative, so the
        rebind propagates without any relay cooperation)."""
        self._relays = (
            list(relay) if isinstance(relay, (list, tuple)) else [relay]
        )
        if not self._relays:
            raise ValueError("use_relay: empty relay list")
        self._relay_idx = 0
        self._register_with(self._relays[0])

        async def rereg():
            while True:
                await asyncio.sleep(reregister_every)
                self._maybe_failover_relay()
                assert self._my_relay is not None
                self.send_to(self._my_relay.public_key, wire.relay_register())

        try:
            self._reregister_task = asyncio.get_running_loop().create_task(
                rereg()
            )
        except RuntimeError:
            # no loop (offline construction): without periodic
            # re-registration the relay's TTL expires in 90s and reverse
            # delivery silently stops — surface it instead of skipping
            logger.warning(
                "use_relay without a running event loop: relay "
                "re-registration NOT scheduled; caller must re-register"
            )
            metrics.inc("network_relay_reregister_skipped_total")

    def _register_with(self, relay: PeerAddress) -> None:
        self._my_relay = relay
        self.add_peer(relay, authoritative=True)
        self.send_to(relay.public_key, wire.relay_register())

    def _maybe_failover_relay(self) -> None:
        """Rotate to the next configured relay when the current one has
        stopped accepting our traffic. The signal is the relay WORKER's
        consecutive-failure counter — the same health signal that drives
        its backoff — so a relay that merely drops reverse traffic but
        still ACKs ours is out of scope (peers' message_request recovery
        covers that loss)."""
        if len(self._relays) < 2 or self._my_relay is None:
            return
        worker = self._workers.get(self._my_relay.public_key)
        if (
            worker is None
            or worker.consecutive_failures < self.relay_failover_after
        ):
            return
        self._relay_idx = (self._relay_idx + 1) % len(self._relays)
        new = self._relays[self._relay_idx]
        logger.warning(
            "relay %s unresponsive (%d consecutive failures): failing over "
            "to %s:%d",
            self._my_relay.public_key.hex()[:16],
            worker.consecutive_failures,
            new.host,
            new.port,
        )
        metrics.inc("network_relay_failovers_total")
        self._register_with(new)
        # our advertised address just changed (the relay sentinel embeds
        # the relay's pubkey): push the rebind to every peer now — the
        # self-declared address in a peers_request is authoritative
        adv_host, adv_port = self.advertised_host_port
        for pub, w in self._workers.items():
            if pub != new.public_key:
                w.enqueue(wire.peers_request(adv_host, adv_port))

    @property
    def advertised_host_port(self):
        """What we tell peers to reach us at: the relay sentinel when
        NAT'd, the real listening address otherwise."""
        if self._my_relay is not None:
            return wire.relay_host(self._my_relay.public_key), 0
        return self.advertise_host, self.hub.port

    def _relay_transport(self, target_pub: bytes, relay_pub: bytes):
        """ClientWorker transport for a relay-routed peer: wrap each signed
        batch in a relay_forward and queue it to the RELAY's worker."""

        async def send(_peer, batch_bytes: bytes) -> bool:
            relay_worker = self._workers.get(relay_pub)
            if relay_worker is None:
                return False
            relay_worker.enqueue(
                wire.relay_forward(target_pub, batch_bytes)
            )
            return True

        return send

    @property
    def address(self) -> PeerAddress:
        return PeerAddress(self.public_key, self.hub.host, self.hub.port)

    def add_peer(self, peer: PeerAddress, authoritative: bool = True) -> None:
        """Install (or update) the dialing address for a peer.

        `authoritative` addresses come from config or from the peer ITSELF
        (a peers_request rides a signature-verified batch from that pubkey)
        and may REPLACE an existing binding — a restarted peer on a new
        port, or a binding poisoned by bogus gossip, corrects itself the
        moment the real peer makes contact. Third-party gossip
        (peers_reply entries) is non-authoritative: it can only introduce
        UNKNOWN peers, never rebind a known one, so a Byzantine address
        book cannot blackhole traffic to a validator we already reach.

        A host of the form "~<relay pub hex>" (wire.relay_host) marks a
        peer reachable only THROUGH that relay: its worker sends
        relay_forward envelopes to the relay instead of dialing.
        """
        if peer.public_key == self.public_key:
            return
        relay_pub = wire.parse_relay_host(peer.host)
        if relay_pub is not None:
            if relay_pub == self.public_key:
                # we ARE this peer's relay: it reaches us inbound; traffic
                # back to it rides its own connection (send_to fallback).
                # It must be a registered client to be deliverable at all.
                for msg in self._undelivered.pop(peer.public_key, ()):
                    self.send_to(peer.public_key, msg)
                return
            if relay_pub not in self._workers:
                logger.info(
                    "peer %s advertises unknown relay %s; dropped",
                    peer.public_key.hex()[:16], relay_pub.hex()[:16],
                )
                return
            old_route = self._relay_route.get(peer.public_key)
            if old_route == relay_pub and peer.public_key in self._workers:
                return
            if not authoritative and peer.public_key in self._workers:
                # third-party gossip may only INTRODUCE unknown peers — it
                # can neither demote a direct binding NOR move an existing
                # relay route to a different relay (a Byzantine address
                # book would blackhole the victim's traffic at a relay
                # holding no registration for it)
                return
            self._relay_route[peer.public_key] = relay_pub
            old = self._workers.pop(peer.public_key, None)
            if old is not None:
                try:
                    asyncio.get_running_loop().create_task(old.stop())
                except RuntimeError:
                    # no running loop (offline construction/tests): the
                    # worker's tasks were never started, nothing to stop
                    logger.debug(
                        "no running loop; old relay worker for %s dropped "
                        "without async stop",
                        peer.public_key.hex()[:16],
                    )
            worker = ClientWorker(
                peer, self.factory, self.hub,
                flush_interval=self._flush_interval,
                transport=self._relay_transport(peer.public_key, relay_pub),
            )
            self._workers[peer.public_key] = worker
            worker.start()
            host, port = self.advertised_host_port
            worker.enqueue(wire.peers_request(host, port))
            for msg in self._undelivered.pop(peer.public_key, ()):
                worker.enqueue(msg)
            return
        old = self._workers.get(peer.public_key)
        if old is not None:
            if not authoritative or (
                old.peer.host == peer.host and old.peer.port == peer.port
            ):
                # REJECTED updates must not touch state: popping the relay
                # route before this check let refused Byzantine gossip
                # erase a relay-routed peer's entry (its next re-advert
                # then tore down and recreated the worker, dropping its
                # queued consensus messages)
                return
        # accepted direct binding: it supersedes any relay route
        self._relay_route.pop(peer.public_key, None)
        if old is not None:
            # self-declared address change: rebind
            logger.info(
                "peer %s rebinds %s:%d -> %s:%d",
                peer.public_key.hex()[:16],
                old.peer.host, old.peer.port, peer.host, peer.port,
            )
            self._workers.pop(peer.public_key, None)
            try:
                asyncio.get_running_loop().create_task(old.stop())
            except RuntimeError:  # no running loop (tests)
                logger.debug(
                    "no running loop; rebound worker for %s dropped "
                    "without async stop",
                    peer.public_key.hex()[:16],
                )
        worker = ClientWorker(
            peer, self.factory, self.hub,
            flush_interval=self._flush_interval,
        )
        self._workers[peer.public_key] = worker
        worker.start()
        # gossip crawl: ask every new acquaintance for its address book,
        # carrying our own dialable address so it can dial back
        # (config-seeded + gossip-learned peers; reference reaches peers
        # through bootstrap relays, HubConnector.cs:26-105 +
        # config_mainnet.json:22-33)
        adv_host, adv_port = self.advertised_host_port
        worker.enqueue(wire.peers_request(adv_host, adv_port))
        for msg in self._undelivered.pop(peer.public_key, ()):
            worker.enqueue(msg)

    @property
    def peers(self) -> List[bytes]:
        return list(self._workers.keys())

    # -- sending -----------------------------------------------------------

    def wire_version_of(self, public_key: bytes) -> Optional[int]:
        """The wire version `public_key` has advertised, None when it never
        has (legacy peer or no traffic yet)."""
        hs = self.peer_versions.get(public_key)
        return hs.wire_version if hs is not None else None

    def _version_gated(self, public_key: bytes, msg: NetworkMessage) -> bool:
        """True when `msg` must NOT be sent to `public_key`: the peer has
        EXPLICITLY advertised a wire version too old to decode the kind
        (its decoder would reject the whole batch, dropping innocent
        messages sharing the flush). Unknown peers are never gated —
        pre-handshake fleets keep the status quo."""
        advertised = self.wire_version_of(public_key)
        if advertised is None:
            return False
        if advertised >= wire.KIND_MIN_WIRE.get(msg.kind, 1):
            return False
        metrics.inc(
            "network_msgs_version_gated_total",
            labels={"kind": str(msg.kind)},
        )
        logger.debug(
            "kind=%d gated toward peer %s (advertised wire v%d)",
            msg.kind, public_key.hex()[:16], advertised,
        )
        return True

    def send_to(self, public_key: bytes, msg: NetworkMessage) -> None:
        if self._version_gated(public_key, msg):
            return
        if msg.kind == wire.KIND_PING_REQUEST:
            self.rtt.note_sent(public_key)
        worker = self._workers.get(public_key)
        if worker is None:
            self._prune_relay_clients()
            if public_key in self.relay_clients:
                # OUR registered NAT'd client: answer over its own inbound
                # connection (the only path that reaches it)
                batch = self.factory.batch([msg])
                self._send_inbound(public_key, batch.encode(), msg)
                return
            self._buffer_undelivered(public_key, msg)
            return
        worker.enqueue(msg)

    def _buffer_undelivered(self, public_key: bytes, msg) -> None:
        pending = self._undelivered.setdefault(public_key, [])
        if len(pending) < self._undelivered_cap:
            pending.append(msg)
        else:
            # a silently-vanished consensus message here is exactly the
            # wedged-era failure mode: make the loss observable so the
            # metric can alarm and the log names the victim
            logger.warning(
                "undelivered buffer full for peer %s: dropping kind=%d",
                public_key.hex()[:16],
                msg.kind,
            )
            metrics.inc(
                "network_undelivered_dropped_total",
                labels={"kind": str(msg.kind)},
            )

    def _send_inbound(
        self, public_key: bytes, data: bytes, msg=None
    ) -> None:
        """Reverse-deliver to a relay client. `msg` (when given) is
        re-buffered on failure — consensus protocols do not retransmit,
        so a message lost while the client re-dials would wedge an era
        (same rationale as the _undelivered buffer for direct peers).
        The buffer drains when the client's next batch arrives
        (_on_raw_batch refreshes _last_conn and drains)."""
        conn_id = self._last_conn.get(public_key)
        if conn_id is None:
            if msg is not None:
                self._buffer_undelivered(public_key, msg)
            return

        async def deliver():
            ok = await self.hub.send_on_conn(conn_id, data)
            if not ok and msg is not None:
                self._buffer_undelivered(public_key, msg)

        try:
            asyncio.get_running_loop().create_task(deliver())
        except RuntimeError:
            # no running loop: reverse delivery needs the hub's socket,
            # so the message can only wait for the client's next contact
            logger.debug(
                "no running loop; reverse delivery to %s buffered",
                public_key.hex()[:16],
            )
            if msg is not None:
                self._buffer_undelivered(public_key, msg)

    def _prune_relay_clients(self) -> None:
        import time

        now = time.monotonic()
        expired = [
            p for p, t in self.relay_clients.items()
            if now - t > self._relay_client_ttl
        ]
        for p in expired:
            del self.relay_clients[p]
            self._last_conn.pop(p, None)

    def broadcast(self, msg: NetworkMessage) -> None:
        for pub, worker in self._workers.items():
            if self._version_gated(pub, msg):
                continue
            if msg.kind == wire.KIND_PING_REQUEST:
                self.rtt.note_sent(pub)
            worker.enqueue(msg)

    # -- failure handling ----------------------------------------------------

    def install_faults(self, plan, my_id: int, salt: Optional[int] = None):
        """Wire a FaultPlan into this node's TCP path: frames to peers run
        the plan's link decisions (dst resolved by worker pubkey -> the
        index the caller registers via `map_fault_peer`). Returns the
        TcpFrameFilter so tests/CLI can read its stats."""
        from .faults import TcpFrameFilter

        session = plan.session(salt=my_id if salt is None else salt)
        self._fault_peer_ids: Dict[bytes, int] = {}

        def peer_index(peer) -> Optional[int]:
            if peer is None:
                return None
            return self._fault_peer_ids.get(peer.public_key)

        filt = TcpFrameFilter(session, my_id, peer_index)
        self.hub.frame_filter = filt
        return filt

    def map_fault_peer(self, public_key: bytes, node_id: int) -> None:
        """Tell the installed fault filter which plan node a transport
        identity is (link-level partitions/crashes need the mapping)."""
        getattr(self, "_fault_peer_ids", {})[public_key] = node_id

    def _reconnect_allowed(self, public_key: bytes, now: float) -> bool:
        """Spend one token from `public_key`'s reconnect bucket. Refill is
        one token per reconnect_min_interval, with the interval stretched
        by the fleet RTT estimate: on a 200 ms-RTT fleet a strike-3 cycle
        fires on a loopback-tuned schedule, and uncapped it would tear
        down and re-dial a slow-but-alive peer's connection faster than
        the handshake + zlib warmup it just threw away."""
        interval = self.rtt.scale(self.reconnect_min_interval)
        bucket = self._reconnect_buckets.get(public_key)
        if bucket is None:
            bucket = self._reconnect_buckets[public_key] = [
                self.reconnect_bucket_capacity, now
            ]
        tokens, last = bucket
        tokens = min(
            self.reconnect_bucket_capacity,
            tokens + (now - last) / interval,
        )
        if tokens < 1.0:
            bucket[0], bucket[1] = tokens, now
            return False
        bucket[0], bucket[1] = tokens - 1.0, now
        return True

    def reconnect_peers(self, *, force: bool = False) -> int:
        """Stall-escalation last resort: drop cached outbound sockets and
        reset worker backoff, so the next flush re-dials immediately
        instead of waiting out an exponential-backoff window against a
        peer that already recovered. Rationed per peer through an
        RTT-scaled token bucket (`force=True` bypasses — operator CLI);
        returns the number of peers actually reconnected."""
        import time

        now = time.monotonic()
        reconnected = 0
        for pub, worker in self._workers.items():
            if not force and not self._reconnect_allowed(pub, now):
                metrics.inc("watchdog_reconnects_suppressed_total")
                logger.info(
                    "reconnect of peer %s suppressed (token bucket)",
                    pub.hex()[:16],
                )
                continue
            key = (worker.peer.host, worker.peer.port)
            conn = self.hub._conns.pop(key, None)
            if conn is not None:
                conn.close()
            worker.reset_backoff()
            reconnected += 1
        if reconnected:
            metrics.inc("network_forced_reconnect_total")
            logger.warning(
                "forcing reconnect of %d peer connections", reconnected
            )
        return reconnected

    # -- receiving ---------------------------------------------------------

    def _on_raw_batch(self, data: bytes, conn_id: Optional[int] = None) -> None:
        try:
            batch = MessageBatch.decode(data)
        except ValueError:
            logger.warning("undecodable batch dropped")
            return
        if not batch.verify():
            logger.warning("batch with bad signature dropped")
            return
        try:
            msgs = batch.messages()
        except (ValueError, zlib.error):
            logger.warning("corrupt batch content dropped")
            return
        self._note_trace_ctx(batch)
        self._note_handshake(batch)
        if conn_id is not None:
            # remember the latest live inbound connection per verified
            # sender: the reverse-delivery path to NAT'd relay clients.
            # A reconnecting client also drains anything buffered while
            # its connection was down.
            self._last_conn[batch.sender] = conn_id
            if batch.sender in self.relay_clients:
                for m in self._undelivered.pop(batch.sender, ()):
                    self.send_to(batch.sender, m)
        for msg in msgs:
            try:
                self._dispatch(batch.sender, msg)
            except Exception:
                logger.exception("message handler failed")

    def _note_trace_ctx(self, batch: MessageBatch) -> None:
        """Record the sender's trace context from a VERIFIED batch: the
        receiving node's consensus spans for that era can then carry the
        peer's trace id (cross-node causality for RBC echo/ready and coin
        shares in the merged fleet trace). First sighting of an id per era
        emits a wire.trace_ctx instant; repeats are a set probe."""
        ctx = batch.trace_trailer()
        if ctx is None:
            return
        origin, era, tid = ctx
        ids = self.era_trace_ids.get(era)
        if ids is None:
            ids = self.era_trace_ids[era] = set()
            while len(self.era_trace_ids) > self._TRACE_ERA_KEEP:
                del self.era_trace_ids[min(self.era_trace_ids)]
        tid_hex = tid.hex()
        if tid_hex not in ids:
            ids.add(tid_hex)
            from ..utils import tracing

            tracing.instant(
                "wire.trace_ctx",
                cat="net",
                era=era,
                trace=tid_hex,
                origin=origin.hex(),
                sender=batch.sender.hex()[:16],
            )

    def _note_handshake(self, batch: MessageBatch) -> None:
        """Record the sender's advertised versions from a VERIFIED batch.
        Logged on first sighting and on change (a mid-roll restart flips a
        peer's version); incompatible peers are surfaced loudly but NOT
        disconnected — the adjacency contract makes |Δ|<=1 interoperable,
        and anything wider is an operator error the metric should page on,
        not a reason to shrink quorum further."""
        hs = batch.handshake()
        if hs is None:
            return
        prev = self.peer_versions.get(batch.sender)
        if prev == hs:
            return
        self.peer_versions[batch.sender] = hs
        metrics.set_gauge(
            "network_peer_wire_version",
            hs.wire_version,
            labels={"peer": batch.sender[:4].hex()},
        )
        logger.info(
            "peer %s advertises wire v%d engine v%d features=0x%x",
            batch.sender.hex()[:16],
            hs.wire_version, hs.engine_version, hs.features,
        )
        if not wire.compatible(hs.wire_version, self.factory.wire_version):
            metrics.inc("network_peer_version_incompatible_total")
            logger.error(
                "peer %s wire v%d is OUTSIDE the v%d±1 compatibility "
                "window — upgrade lag exceeds one version",
                batch.sender.hex()[:16],
                hs.wire_version, self.factory.wire_version,
            )

    def trace_ids_for(self, era: int) -> List[str]:
        """Trace ids seen on inbound consensus traffic for `era` (sorted
        for deterministic span annotations)."""
        return sorted(self.era_trace_ids.get(era, ()))

    def _dispatch(self, sender: bytes, msg: NetworkMessage) -> None:
        k = msg.kind
        if k == wire.KIND_CONSENSUS and self.on_consensus:
            era, payload = wire.parse_consensus(msg)
            self.on_consensus(sender, era, payload)
        elif k == wire.KIND_PING_REQUEST and self.on_ping_request:
            self.on_ping_request(sender, wire.parse_height(msg))
        elif k == wire.KIND_PING_REPLY:
            # RTT sample first: the ping exchange doubles as the WAN
            # latency instrument (network/rtt.py)
            self.rtt.note_reply(sender)
            w = self._workers.get(sender)
            if w is not None:
                # redial pacing floor: retrying faster than the link's
                # RTT burns dials that cannot have completed yet
                w.backoff_floor = self.rtt.srtt(sender) or 0.0
            if self.on_ping_reply:
                self.on_ping_reply(sender, wire.parse_height(msg))
        elif k == wire.KIND_SYNC_BLOCKS_REQUEST and self.on_sync_blocks_request:
            start, count = wire.parse_sync_blocks_request(msg)
            self.on_sync_blocks_request(sender, start, count)
        elif k == wire.KIND_SYNC_BLOCKS_REPLY and self.on_sync_blocks_reply:
            self.on_sync_blocks_reply(sender, wire.parse_sync_blocks_reply(msg))
        elif k == wire.KIND_SYNC_POOL_REQUEST and self.on_sync_pool_request:
            self.on_sync_pool_request(sender, wire.parse_sync_pool_request(msg))
        elif k == wire.KIND_SYNC_POOL_REPLY and self.on_sync_pool_reply:
            self.on_sync_pool_reply(sender, wire.parse_sync_pool_reply(msg))
        elif k == wire.KIND_FAST_SYNC_REQUEST and self.on_fast_sync_request:
            self.on_fast_sync_request(sender, wire.parse_fast_sync_request(msg))
        elif k == wire.KIND_FAST_SYNC_REPLY and self.on_fast_sync_reply:
            self.on_fast_sync_reply(sender, *wire.parse_fast_sync_reply(msg))
        elif k == wire.KIND_TRIE_NODES_REQUEST and self.on_trie_nodes_request:
            self.on_trie_nodes_request(sender, wire.parse_trie_nodes_request(msg))
        elif k == wire.KIND_TRIE_NODES_REPLY and self.on_trie_nodes_reply:
            self.on_trie_nodes_reply(sender, wire.parse_trie_nodes_reply(msg))
        elif k == wire.KIND_TRIE_NODES_REQUEST_ID and self.on_trie_nodes_request_id:
            rid, hashes = wire.parse_trie_nodes_request_id(msg)
            self.on_trie_nodes_request_id(sender, rid, hashes)
        elif k == wire.KIND_TRIE_NODES_REPLY_ID and self.on_trie_nodes_reply_id:
            rid, nodes = wire.parse_trie_nodes_reply_id(msg)
            self.on_trie_nodes_reply_id(sender, rid, nodes)
        elif k == wire.KIND_SNAPSHOT_REQUEST and self.on_snapshot_request:
            rid, cursor, limit = wire.parse_snapshot_request(msg)
            self.on_snapshot_request(sender, rid, cursor, limit)
        elif k == wire.KIND_SNAPSHOT_REPLY and self.on_snapshot_reply:
            rid, next_cursor, done, records = wire.parse_snapshot_reply(msg)
            self.on_snapshot_reply(sender, rid, next_cursor, done, records)
        elif k == wire.KIND_MESSAGE_REQUEST and self.on_message_request:
            self.on_message_request(sender, wire.parse_message_request(msg))
        elif k == wire.KIND_PEERS_REQUEST:
            self._on_peers_request(sender, msg)
        elif k == wire.KIND_PEERS_REPLY:
            self._on_peers_reply(msg)
        elif k == wire.KIND_RELAY_REGISTER:
            self._on_relay_register(sender)
        elif k == wire.KIND_RELAY_FORWARD:
            self._on_relay_forward(sender, msg)

    # -- relaying ----------------------------------------------------------

    def _on_relay_register(self, sender: bytes) -> None:
        import time

        now = time.monotonic()
        fresh = sender not in self.relay_clients
        self.relay_clients[sender] = now
        self._prune_relay_clients()
        if fresh:
            logger.info(
                "relay client registered: %s", sender.hex()[:16]
            )
            # the client may have been buffered as undeliverable before
            for m in self._undelivered.pop(sender, ()):
                self.send_to(sender, m)

    def _on_relay_forward(self, sender: bytes, msg: NetworkMessage) -> None:
        try:
            target, inner = wire.parse_relay_forward(msg)
        except ValueError:
            logger.warning("malformed relay_forward dropped")
            return
        if target == self.public_key:
            # an envelope addressed to US (we are the NAT'd node and the
            # relay delivered over our outbound conn): unwrap and process
            # the inner batch — its own signature authenticates the origin
            self._on_raw_batch(inner)
            return
        self._prune_relay_clients()
        if target not in self.relay_clients:
            logger.warning(
                "relay_forward from %s for unregistered %s dropped",
                sender.hex()[:16], target.hex()[:16],
            )
            return
        self._send_inbound(target, inner)

    # -- gossip peer discovery ---------------------------------------------

    def _on_peers_request(self, sender: bytes, msg: NetworkMessage) -> None:
        host, port = wire.parse_peers_request(msg)
        # the requester's self-declared address arrived under its own batch
        # signature: authoritative (installs OR rebinds), so an inbound-only
        # acquaintance gets a worker to carry the reply
        self.add_peer(
            PeerAddress(public_key=sender, host=host, port=port),
            authoritative=True,
        )
        book = [
            (w.peer.public_key, w.peer.host, w.peer.port)
            for w in self._workers.values()
            if w.peer.public_key != sender
        ]
        # our registered NAT'd clients are reachable THROUGH us (pruned
        # first: a dead client must not be advertised into a void)
        self._prune_relay_clients()
        me = wire.relay_host(self.public_key)
        for pub in self.relay_clients:
            if pub != sender:
                book.append((pub, me, 0))
        adv_host, adv_port = self.advertised_host_port
        book.append((self.public_key, adv_host, adv_port))
        self.send_to(sender, wire.peers_reply(book))

    def _on_peers_reply(self, msg: NetworkMessage) -> None:
        try:
            entries = wire.parse_peers_reply(msg)
        except ValueError:
            logger.warning("malformed peers reply dropped")
            return
        for pub, host, port in entries:
            if pub == self.public_key or pub in self._workers:
                continue
            peer = PeerAddress(public_key=pub, host=host, port=port)
            # third-party gossip: may only INTRODUCE unknown peers
            self.add_peer(peer, authoritative=False)
            if self.on_peer_discovered:
                try:
                    self.on_peer_discovered(peer)
                except Exception:
                    logger.exception("peer-discovered handler failed")
