"""Deterministic fault injection: one plan, every delivery layer.

A :class:`FaultPlan` is a seeded, declarative description of an adversarial
network — message loss/delay/duplication/reordering probabilities, link-level
partitions with heal times, and scheduled peer crash/restart windows. The
same plan object drives three delivery layers:

  * the in-process simulator (`consensus/simulator.py`) — virtual clock is
    the delivered-message count, recovery is modeled by outbox replay on
    quiescence;
  * the native engine (`consensus/native_rt.py`) — the plan maps onto the
    engine's own knobs (duplicate ppm, reorder mode, muted players);
  * the real TCP path (`network/hub.py`) — a :class:`TcpFrameFilter` built
    from the plan drops/delays/duplicates framed batches on the socket,
    clocked by wall time.

Every probabilistic decision draws from a `random.Random` seeded from
`(plan.seed, salt)`: a layer replaying the same decision sequence replays
the same faults, which is what makes a recorded production failure
reproducible from its seed (HoneyBadgerBFT only guarantees liveness under
eventual delivery — the recovery layer must be provoked deterministically
to be testable at all).

Time units are layer-relative: the simulator clocks in delivered messages,
the TCP filter in seconds since installation. A plan authored for one layer
therefore needs its schedule rescaled for the other; probabilities carry
over unchanged.

WAN emulation rides on the same contract: a :class:`LinkShaper` attached to
the plan gives every (region, region) link a base latency, jitter (with
seeded burst windows), and a bandwidth cap enforced by a per-link pacer.
Shaped latency is expressed through the existing `decide()` delay-list
interface, so the simulator, the TCP frame filter, and the hub's delay
timers all carry it with no extra plumbing — and the decisions draw from
the same seeded rng, so two same-seed runs shape bit-identically.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Mapping, Optional, Tuple

from ..utils import metrics


@dataclass(frozen=True)
class Crash:
    """Node `node` crashes at `at` and restarts at `restart` (None = never).

    A crashed node neither sends nor processes; on restart it rejoins with
    its in-memory state intact (process-level restart with state loss is the
    block-sync path, not this layer's job)."""

    node: int
    at: float
    restart: Optional[float] = None


@dataclass(frozen=True)
class Partition:
    """Link-level split: traffic between `side_a` and `side_b` is blocked
    from `at` until `heal` (None = never heals). Intra-side traffic and
    nodes on neither side are unaffected."""

    side_a: FrozenSet[int]
    side_b: FrozenSet[int]
    at: float
    heal: Optional[float] = None


@dataclass(frozen=True)
class LinkShape:
    """One directed region->region link's shape, in the layer's clock/size
    units (seconds + bytes on TCP, virtual ticks + nominal frame units in
    the simulator)."""

    latency: float = 0.0    # one-way base latency
    jitter: float = 0.0     # uniform extra delay in [0, jitter]
    bandwidth: float = 0.0  # link capacity, size units per clock unit; 0 = uncapped


@dataclass(frozen=True)
class LinkShaper:
    """Seeded WAN link shaping: a per-region-pair latency/jitter/bandwidth
    matrix applied to every frame a FaultSession decides on.

    Node -> region assignment is positional (`regions[node % len]`), so a
    16-node fleet over `("us", "eu", "ap", "sa")` stripes four emulated
    regions. Links are DIRECTED: `links[("us", "eu")]` may differ from
    `links[("eu", "ap")]` (asymmetric paths); a missing ordered pair falls
    back to the reversed pair, then to `default` for cross-region links.
    Intra-region links are unshaped unless an explicit ("r", "r") entry or
    `intra` exists. Jitter draws come from the session's seeded rng and
    occasionally land in burst windows (`jitter_burst` probability) where
    the draw is amplified `burst_multiplier`x — the WAN microburst model.
    The bandwidth cap is a per-link serialization pacer: frame `k` cannot
    start before frame `k-1` finished transmitting at `bandwidth`
    units/clock-unit, so a flood on a thin link accumulates queueing delay
    exactly like a real egress buffer."""

    regions: Tuple[str, ...] = ()
    links: Mapping[Tuple[str, str], LinkShape] = field(default_factory=dict)
    default: LinkShape = field(default_factory=LinkShape)
    intra: Optional[LinkShape] = None
    jitter_burst: float = 0.0
    burst_multiplier: float = 4.0

    def region_of(self, node: int) -> str:
        if not self.regions:
            return ""
        return self.regions[node % len(self.regions)]

    def link(self, src: int, dst: int) -> Optional[LinkShape]:
        """The shape governing src->dst traffic, None = unshaped."""
        rs, rd = self.region_of(src), self.region_of(dst)
        shape = self.links.get((rs, rd))
        if shape is None:
            shape = self.links.get((rd, rs))
        if shape is None:
            if rs == rd:
                shape = self.intra
            else:
                shape = self.default
        return shape

    # -- spec parsing (CLI flags / config strings / compose env) ------------

    @staticmethod
    def _dur(s: str) -> float:
        """"40ms" / "1.5s" -> seconds; a bare float passes through (clock
        units of whatever layer runs the plan)."""
        s = s.strip()
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        return float(s)

    @staticmethod
    def _rate(s: str) -> float:
        """"4mbps" / "512kbps" -> bytes/second; a bare float passes
        through (size units per clock unit)."""
        s = s.strip().lower()
        if s.endswith("mbps"):
            return float(s[:-4]) * 125_000.0
        if s.endswith("kbps"):
            return float(s[:-4]) * 125.0
        if s.endswith("bps"):
            return float(s[:-3]) / 8.0
        return float(s)

    @classmethod
    def _shape_of(cls, spec: str) -> LinkShape:
        """"LAT[/JITTER][@BW]" — e.g. "80ms/8ms@4mbps", "35ms", "3@2"."""
        bw = 0.0
        if "@" in spec:
            spec, _, bw_s = spec.partition("@")
            bw = cls._rate(bw_s)
        lat_s, _, jit_s = spec.partition("/")
        return LinkShape(
            latency=cls._dur(lat_s),
            jitter=cls._dur(jit_s) if jit_s else 0.0,
            bandwidth=bw,
        )

    @classmethod
    def parse(cls, spec: str) -> "LinkShaper":
        """Parse a compact shaper spec, e.g.::

            regions=us,eu,ap,sa;default=80ms/8ms@4mbps;us-eu=35ms;\
intra=2ms;burst=0.01x8

        Items are ';'-separated `key=value` pairs: `regions` (positional
        node->region stripes), `default` (cross-region fallback shape),
        `intra` (same-region shape), `burst=PxM` (jitter burst probability
        P, multiplier M), and `A-B=SHAPE` directed region-pair entries."""
        regions: Tuple[str, ...] = ()
        links: Dict[Tuple[str, str], LinkShape] = {}
        default = LinkShape()
        intra: Optional[LinkShape] = None
        burst_p, burst_m = 0.0, 4.0
        for item in spec.split(";"):
            item = item.strip()
            if not item:
                continue
            key, _, val = item.partition("=")
            if not val:
                raise ValueError(f"shaper spec item {item!r}: expected key=value")
            key = key.strip()
            if key == "regions":
                regions = tuple(r.strip() for r in val.split(",") if r.strip())
            elif key == "default":
                default = cls._shape_of(val)
            elif key == "intra":
                intra = cls._shape_of(val)
            elif key == "burst":
                p_s, _, m_s = val.partition("x")
                burst_p = float(p_s)
                burst_m = float(m_s) if m_s else 4.0
            elif "-" in key:
                a, _, b = key.partition("-")
                links[(a.strip(), b.strip())] = cls._shape_of(val)
            else:
                raise ValueError(f"shaper spec item {item!r}: unknown key")
        return cls(
            regions=regions,
            links=links,
            default=default,
            intra=intra,
            jitter_burst=burst_p,
            burst_multiplier=burst_m,
        )


@dataclass(frozen=True)
class FaultPlan:
    """Seeded adversarial schedule. All probabilities are per-message."""

    seed: int = 0
    drop: float = 0.0        # message silently lost
    duplicate: float = 0.0   # message delivered twice
    delay: float = 0.0       # message deferred (re-queued / timer-delayed)
    reorder: float = 0.0     # message swapped with a random queued one
    delay_span: Tuple[float, float] = (1.0, 16.0)  # sampled delay bounds
    partitions: Tuple[Partition, ...] = ()
    crashes: Tuple[Crash, ...] = ()
    # WAN link shaping (latency matrix / jitter bursts / bandwidth pacing);
    # None = loopback-flat links, the pre-WAN behavior
    shaper: Optional[LinkShaper] = None

    def session(
        self, clock: Optional[Callable[[], float]] = None, salt: int = 0
    ) -> "FaultSession":
        """A live decision stream for one delivery layer. `clock` supplies
        the layer's notion of now (defaults to seconds since creation);
        `salt` decorrelates per-node streams over TCP, where each node owns
        its outbound decisions and there is no global draw order."""
        return FaultSession(self, clock=clock, salt=salt)

    # -- schedule queries (clock-explicit; sessions wrap these) -------------

    def crashed(self, node: int, now: float) -> bool:
        for c in self.crashes:
            if c.node == node and c.at <= now and (
                c.restart is None or now < c.restart
            ):
                return True
        return False

    def partitioned(self, a: int, b: int, now: float) -> bool:
        for p in self.partitions:
            if p.at <= now and (p.heal is None or now < p.heal):
                if (a in p.side_a and b in p.side_b) or (
                    a in p.side_b and b in p.side_a
                ):
                    return True
        return False

    def next_boundary(self, after: float) -> Optional[float]:
        """Earliest schedule edge strictly after `after` — the point a
        quiesced simulator must jump its virtual clock to, so partitions
        heal and crashed nodes restart even with no traffic in flight."""
        edges: List[float] = []
        for c in self.crashes:
            edges.extend(t for t in (c.at, c.restart) if t is not None)
        for p in self.partitions:
            edges.extend(t for t in (p.at, p.heal) if t is not None)
        future = [t for t in edges if t > after]
        return min(future) if future else None

    # -- CLI spec parsing ----------------------------------------------------

    @staticmethod
    def parse_crash(spec: str) -> Crash:
        """"NODE@AT[:RESTART]" — e.g. "1@400:1200", "2@300"."""
        node_s, _, times = spec.partition("@")
        if not times:
            raise ValueError(f"crash spec {spec!r}: expected NODE@AT[:RESTART]")
        at_s, _, restart_s = times.partition(":")
        return Crash(
            node=int(node_s),
            at=float(at_s),
            restart=float(restart_s) if restart_s else None,
        )

    @staticmethod
    def parse_partition(spec: str) -> Partition:
        """"A,B|C,D@AT[:HEAL]" — e.g. "0,1|2,3@300:900"."""
        sides, _, times = spec.partition("@")
        if not times:
            raise ValueError(
                f"partition spec {spec!r}: expected A,B|C,D@AT[:HEAL]"
            )
        a_s, _, b_s = sides.partition("|")
        if not b_s:
            raise ValueError(f"partition spec {spec!r}: missing '|'")
        at_s, _, heal_s = times.partition(":")
        return Partition(
            side_a=frozenset(int(x) for x in a_s.split(",") if x),
            side_b=frozenset(int(x) for x in b_s.split(",") if x),
            at=float(at_s),
            heal=float(heal_s) if heal_s else None,
        )


class FaultSession:
    """One layer's live execution of a FaultPlan: seeded rng + stats.

    All decisions are drawn from a private `random.Random((seed << 20) ^
    salt)`; a layer that replays the same sequence of `decide()` calls
    replays the same faults."""

    def __init__(
        self,
        plan: FaultPlan,
        clock: Optional[Callable[[], float]] = None,
        salt: int = 0,
    ):
        import random

        self.plan = plan
        if clock is None:
            t0 = time.monotonic()
            clock = lambda: time.monotonic() - t0  # noqa: E731
        self._clock = clock
        self.rng = random.Random((plan.seed << 20) ^ (salt & 0xFFFFF))
        self.stats: Dict[str, int] = {
            "dropped": 0,
            "duplicated": 0,
            "delayed": 0,
            "reordered": 0,
            "blocked": 0,   # partition / crash suppression
            "delivered": 0,
            "shaped": 0,    # frames that picked up LinkShaper latency
            "bursts": 0,    # jitter draws that landed in a burst window
        }
        # LinkShaper bandwidth pacer: per directed link, the clock time the
        # link's serializer frees up (frame k queues behind frame k-1)
        self._link_free: Dict[Tuple[int, int], float] = {}

    @property
    def now(self) -> float:
        return self._clock()

    # -- schedule state ------------------------------------------------------

    def crashed(self, node: Optional[int]) -> bool:
        return node is not None and self.plan.crashed(node, self.now)

    def partitioned(self, a: Optional[int], b: Optional[int]) -> bool:
        if a is None or b is None:
            return False
        return self.plan.partitioned(a, b, self.now)

    def link_blocked(self, src: Optional[int], dst: Optional[int]) -> bool:
        return (
            self.crashed(src)
            or self.crashed(dst)
            or self.partitioned(src, dst)
        )

    def next_boundary(self, after: Optional[float] = None) -> Optional[float]:
        return self.plan.next_boundary(self.now if after is None else after)

    # -- per-message decisions ----------------------------------------------

    def decide(
        self, src: Optional[int], dst: Optional[int], size: int = 1
    ) -> List[float]:
        """The fate of one message on the src->dst link: a list of delivery
        delays, one per copy. `[]` = dropped, `[0.0]` = delivered now,
        `[0.0, 0.0]` = duplicated, `[d]` = delivered after `d` time units.
        Unknown endpoints (None) skip link-state checks but still roll the
        probabilistic faults. `size` feeds the LinkShaper bandwidth pacer
        (frame bytes on TCP, a nominal 1 unit in the simulator)."""
        p = self.plan
        if self.link_blocked(src, dst):
            self.stats["blocked"] += 1
            metrics.inc("fault_injected_total", labels={"action": "blocked"})
            return []
        if p.drop > 0 and self.rng.random() < p.drop:
            self.stats["dropped"] += 1
            metrics.inc("fault_injected_total", labels={"action": "drop"})
            return []
        delays = [0.0]
        if p.delay > 0 and self.rng.random() < p.delay:
            lo, hi = p.delay_span
            delays[0] = lo + self.rng.random() * (hi - lo)
            self.stats["delayed"] += 1
            metrics.inc("fault_injected_total", labels={"action": "delay"})
        if p.duplicate > 0 and self.rng.random() < p.duplicate:
            delays.append(0.0)
            self.stats["duplicated"] += 1
            metrics.inc("fault_injected_total", labels={"action": "dup"})
        shaped = self._shape(src, dst, size)
        if shaped > 0:
            # every copy of the frame crosses the same WAN link; shifting
            # them all keeps duplicate spacing intact
            delays = [d + shaped for d in delays]
            self.stats["shaped"] += 1
            metrics.inc("fault_injected_total", labels={"action": "shape"})
        self.stats["delivered"] += 1
        return delays

    def _shape(
        self, src: Optional[int], dst: Optional[int], size: int
    ) -> float:
        """LinkShaper latency for one frame: base + (burst-amplified)
        jitter + bandwidth serialization/queueing delay. 0.0 = unshaped
        link. Jitter draws come from the session rng; pacer state advances
        per call — both deterministic given the call sequence, which is the
        same bit-identity contract the rest of the plan honors."""
        shaper = self.plan.shaper
        if shaper is None or src is None or dst is None or src == dst:
            return 0.0
        link = shaper.link(src, dst)
        if link is None:
            return 0.0
        lat = link.latency
        if link.jitter > 0:
            j = self.rng.random() * link.jitter
            if (
                shaper.jitter_burst > 0
                and self.rng.random() < shaper.jitter_burst
            ):
                j *= shaper.burst_multiplier
                self.stats["bursts"] += 1
            lat += j
        if link.bandwidth > 0 and size > 0:
            now = self.now
            start = max(now, self._link_free.get((src, dst), 0.0))
            done = start + size / link.bandwidth
            self._link_free[(src, dst)] = done
            lat += done - now
        return lat

    def reorder_hit(self) -> bool:
        """One roll of the reorder die (the queue owner does the swap)."""
        if self.plan.reorder <= 0 or self.rng.random() >= self.plan.reorder:
            return False
        self.stats["reordered"] += 1
        metrics.inc("fault_injected_total", labels={"action": "reorder"})
        return True


class TcpFrameFilter:
    """Injectable Hub frame filter executing a FaultPlan over real sockets.

    Installed via `Hub.frame_filter` (or `NetworkManager.install_faults`).
    Outbound frames to a mapped peer run the full link decision — a dropped
    frame still reports success to the sender, so loss is only repairable
    by the message-request/outbox-replay layer, exactly like real loss.
    Inbound frames are suppressed only while WE are crashed (probabilistic
    loss is owned by the sending side, so per-link loss is rolled once).
    """

    def __init__(
        self,
        session: FaultSession,
        my_id: int,
        peer_index: Optional[Callable[[object], Optional[int]]] = None,
    ):
        self.session = session
        self.my_id = my_id
        # peer_index(PeerAddress) -> plan node id (None = unmapped peer:
        # link checks are skipped, probabilistic faults still apply)
        self._peer_index = peer_index or (lambda peer: None)

    def outbound(self, peer, data: bytes) -> List[float]:
        dst = self._peer_index(peer) if peer is not None else None
        return self.session.decide(self.my_id, dst, size=len(data))

    def inbound(self, data: bytes) -> List[float]:
        if self.session.crashed(self.my_id):
            self.session.stats["blocked"] += 1
            metrics.inc("fault_injected_total", labels={"action": "blocked"})
            return []
        return [0.0]


class AdversarialRelayFilter:
    """Hub frame filter modelling a MALICIOUS relay rather than a lossy
    link: the node it is installed on selectively forwards, reorders
    (delays), and replays the signed batch frames it emits. Decisions are
    a pure seeded hash of the frame bytes — two runs replay the identical
    attack (the same determinism contract as FaultPlan and
    consensus/adversary.py). Because frames carry batch signatures, honest
    receivers absorb every replay via signature checks + dedupe, and
    selective forwarding is repaired by the outbox-replay layer; the
    chaos and adversary suites pin that. Composes with an inner filter.
    """

    def __init__(
        self,
        seed: int = 0,
        drop_rate: int = 8,  # silently eat 1-in-N frames
        replay_rate: int = 8,  # send 1-in-N frames twice
        reorder_rate: int = 8,  # delay 1-in-N frames by `delay_s`
        delay_s: float = 0.05,
        inner=None,
    ):
        self.seed = seed
        self.drop_rate = drop_rate
        self.replay_rate = replay_rate
        self.reorder_rate = reorder_rate
        self.delay_s = delay_s
        self.inner = inner
        self.stats = {"forwarded": 0, "dropped": 0, "replayed": 0,
                      "reordered": 0}

    def _h(self, tag: bytes, data: bytes) -> int:
        import hashlib

        h = hashlib.blake2b(digest_size=8)
        h.update(str(self.seed).encode())
        h.update(tag)
        h.update(data)
        return int.from_bytes(h.digest(), "big")

    def outbound(self, peer, data: bytes) -> List[float]:
        if self.inner is not None and not self.inner.outbound(peer, data):
            return []
        if self.drop_rate and self._h(b"drop", data) % self.drop_rate == 0:
            self.stats["dropped"] += 1
            metrics.inc(
                "fault_injected_total", labels={"action": "relay_drop"}
            )
            return []
        if self.replay_rate and self._h(b"dup", data) % self.replay_rate == 0:
            self.stats["replayed"] += 1
            metrics.inc(
                "fault_injected_total", labels={"action": "relay_replay"}
            )
            return [0.0, 0.0]
        if (
            self.reorder_rate
            and self._h(b"ord", data) % self.reorder_rate == 0
        ):
            self.stats["reordered"] += 1
            metrics.inc(
                "fault_injected_total", labels={"action": "relay_reorder"}
            )
            return [self.delay_s]
        self.stats["forwarded"] += 1
        return [0.0]

    def inbound(self, data: bytes) -> List[float]:
        if self.inner is not None:
            return self.inner.inbound(data)
        return [0.0]


class KillSwitch:
    """Hub frame filter that makes a node go dark on command.

    `kill()` suppresses every frame in both directions from that moment
    on — to every peer, the node looks exactly like a SIGKILLed process
    whose kernel still holds the sockets open: sends appear to succeed
    (injected loss must look like the network ate it) and nothing ever
    answers. The tier-1 simulated-kill counterpart of the slow tests'
    real SIGKILL: it exercises the same timeout/failover path without
    the subprocess cost. Composes with an inner filter (e.g. a
    TcpFrameFilter running a FaultPlan) applied while still alive.
    """

    def __init__(self, inner=None):
        self.inner = inner
        self._dead = False

    def kill(self) -> None:
        self._dead = True
        metrics.inc("fault_injected_total", labels={"action": "killswitch"})

    @property
    def dead(self) -> bool:
        return self._dead

    def outbound(self, peer, data: bytes) -> List[float]:
        if self._dead:
            return []
        if self.inner is not None:
            return self.inner.outbound(peer, data)
        return [0.0]

    def inbound(self, data: bytes) -> List[float]:
        if self._dead:
            return []
        if self.inner is not None:
            return self.inner.inbound(data)
        return [0.0]
