"""Chain data types: transactions, receipts, block headers, blocks.

Parity with the reference's proto layer
(/root/reference/src/Lachain.Proto: transaction.proto, block.proto) and the
tx-hashing rules (src/Lachain.Crypto/TransactionUtils.cs:1-107). Our wire
format is the framework's fixed-width codec; hashes are keccak256 over the
canonical encoding (chain-id mixed into the signing hash, EIP-155-style).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..crypto import ecdsa
from ..crypto.hashes import keccak256, merkle_root
from ..utils.serialization import (
    Reader,
    write_bytes,
    write_bytes_list,
    write_u32,
    write_u64,
    write_u256,
)

ADDRESS_BYTES = 20
ZERO_ADDRESS = b"\x00" * ADDRESS_BYTES
ZERO_HASH = b"\x00" * 32


@dataclass(frozen=True)
class Transaction:
    """A transfer / contract call (reference: transaction.proto Transaction)."""

    to: bytes  # 20 bytes; ZERO_ADDRESS + invocation => deploy
    value: int  # wei-style u256
    nonce: int
    gas_price: int
    gas_limit: int
    invocation: bytes = b""  # contract input

    def encode(self) -> bytes:
        return (
            self.to
            + write_u256(self.value)
            + write_u64(self.nonce)
            + write_u256(self.gas_price)
            + write_u64(self.gas_limit)
            + write_bytes(self.invocation)
        )

    @classmethod
    def decode(cls, data: bytes) -> "Transaction":
        r = Reader(data)
        to = r.raw(ADDRESS_BYTES)
        value = r.u256()
        nonce = r.u64()
        gas_price = r.u256()
        gas_limit = r.u64()
        invocation = r.bytes_()
        r.assert_eof()
        return cls(to, value, nonce, gas_price, gas_limit, invocation)

    def signing_hash(self, chain_id: int) -> bytes:
        """Hash to sign — chain id mixed in (EIP-155 shape,
        reference TransactionUtils.cs)."""
        return keccak256(self.encode() + write_u64(chain_id))


# (signing_hash, signature) -> recovered address; _MISS marks a signature
# that failed recovery so invalid txs don't retry the recover either
_MISS = object()
_SENDER_MEMO: dict = {}


@dataclass(frozen=True)
class SignedTransaction:
    tx: Transaction
    signature: bytes  # 65-byte recoverable ECDSA

    def encode(self) -> bytes:
        # immutable value object: ordering, pooling, block assembly and
        # hashing all re-encode the same tx many times per era — memoize
        # (the reference's proto objects keep their serialized form too)
        cached = self.__dict__.get("_enc_cache")
        if cached is None:
            cached = write_bytes(self.tx.encode()) + write_bytes(
                self.signature
            )
            object.__setattr__(self, "_enc_cache", cached)
        return cached

    @classmethod
    def decode(cls, data: bytes) -> "SignedTransaction":
        r = Reader(data)
        tx = Transaction.decode(r.bytes_())
        sig = r.bytes_()
        r.assert_eof()
        out = cls(tx, sig)
        # assert_eof proved `data` IS the canonical encoding — seed the
        # memo so wire-decoded txs never pay the re-encode either
        object.__setattr__(out, "_enc_cache", data)
        return out

    def hash(self) -> bytes:
        cached = self.__dict__.get("_hash_cache")
        if cached is None:
            cached = keccak256(self.encode())
            object.__setattr__(self, "_hash_cache", cached)
        return cached

    def sender(self, chain_id: int) -> Optional[bytes]:
        """Recovered 20-byte sender address, or None if invalid. Cached
        per-object AND process-wide: ordering, execution and the pool all
        ask repeatedly, and in-process multi-validator harnesses decode
        the same wire tx into per-validator objects — without the shared
        memo each validator pays the ECDSA recovery again (reference
        caches recoveries in TransactionManager's verify cache,
        TransactionManager.cs:141-171)."""
        cached = self.__dict__.get("_sender_cache")
        if cached is not None and cached[0] == chain_id:
            return cached[1]
        h = self.tx.signing_hash(chain_id)
        key = (h, self.signature)
        addr = _SENDER_MEMO.get(key)
        if addr is _MISS:
            addr = None
        elif addr is None:
            pub = ecdsa.recover_hash(h, self.signature)
            addr = None if pub is None else ecdsa.address_from_public_key(pub)
            if len(_SENDER_MEMO) > 65536:
                _SENDER_MEMO.clear()
            _SENDER_MEMO[key] = addr if addr is not None else _MISS
        object.__setattr__(self, "_sender_cache", (chain_id, addr))
        return addr


def warm_sender_caches(stxs, chain_id: int) -> None:
    """Batch-recover senders for many transactions at once through the
    native threaded entry (ecdsa.recover_hash_batch) and populate each
    tx's sender cache — the pool/sync bulk-ingest fast path (role of the
    reference's background TransactionVerifier,
    Blockchain/Operations/TransactionVerifier.cs:23-72). Safe to call with
    any mix: already-cached txs are skipped, invalid signatures cache a
    None sender exactly like the scalar path."""
    pending = [
        stx
        for stx in stxs
        if (c := stx.__dict__.get("_sender_cache")) is None
        or c[0] != chain_id
    ]
    if not pending:
        return
    pubs = ecdsa.recover_hash_batch(
        [stx.tx.signing_hash(chain_id) for stx in pending],
        [stx.signature for stx in pending],
    )
    for stx, pub in zip(pending, pubs):
        addr = None if pub is None else ecdsa.address_from_public_key(pub)
        object.__setattr__(stx, "_sender_cache", (chain_id, addr))


def sign_transaction(
    tx: Transaction, priv: bytes, chain_id: int
) -> SignedTransaction:
    return SignedTransaction(
        tx=tx, signature=ecdsa.sign_hash(priv, tx.signing_hash(chain_id))
    )


@dataclass(frozen=True)
class TransactionReceipt:
    """Execution result (reference: TransactionReceipt in transaction.proto +
    event.proto logs)."""

    tx_hash: bytes
    block_index: int
    index_in_block: int
    gas_used: int
    status: int  # 1 success, 0 failed
    sender: bytes = ZERO_ADDRESS
    return_data: bytes = b""

    def encode(self) -> bytes:
        return (
            self.tx_hash
            + write_u64(self.block_index)
            + write_u32(self.index_in_block)
            + write_u64(self.gas_used)
            + write_u32(self.status)
            + self.sender
            + write_bytes(self.return_data)
        )

    @classmethod
    def decode(cls, data: bytes) -> "TransactionReceipt":
        r = Reader(data)
        tx_hash = r.raw(32)
        block_index = r.u64()
        index_in_block = r.u32()
        gas_used = r.u64()
        status = r.u32()
        sender = r.raw(ADDRESS_BYTES)
        return_data = r.bytes_()
        r.assert_eof()
        return cls(
            tx_hash, block_index, index_in_block, gas_used, status,
            sender, return_data,
        )


@dataclass(frozen=True)
class BlockHeader:
    """Reference: block.proto BlockHeader (prev hash, merkle root, state hash,
    index, nonce)."""

    index: int
    prev_block_hash: bytes
    merkle_root: bytes  # over tx hashes
    state_hash: bytes
    nonce: int  # from the era's common coin (RootProtocol.cs:316-322)

    def encode(self) -> bytes:
        return (
            write_u64(self.index)
            + self.prev_block_hash
            + self.merkle_root
            + self.state_hash
            + write_u64(self.nonce)
        )

    @classmethod
    def decode(cls, data: bytes) -> "BlockHeader":
        r = Reader(data)
        index = r.u64()
        prev_h = r.raw(32)
        mroot = r.raw(32)
        shash = r.raw(32)
        nonce = r.u64()
        r.assert_eof()
        return cls(index, prev_h, mroot, shash, nonce)

    def hash(self) -> bytes:
        return keccak256(self.encode())


@dataclass(frozen=True)
class MultiSig:
    """Quorum of validator header signatures (reference: multisig.proto)."""

    signatures: Tuple[Tuple[int, bytes], ...]  # (validator index, ecdsa sig)

    def encode(self) -> bytes:
        out = write_u32(len(self.signatures))
        for idx, sig in self.signatures:
            out += write_u32(idx) + write_bytes(sig)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "MultiSig":
        r = Reader(data)
        n = r.u32()
        sigs = tuple((r.u32(), r.bytes_()) for _ in range(n))
        r.assert_eof()
        return cls(sigs)


@dataclass(frozen=True)
class Block:
    header: BlockHeader
    tx_hashes: Tuple[bytes, ...]
    multisig: MultiSig

    def encode(self) -> bytes:
        return (
            write_bytes(self.header.encode())
            + write_bytes_list(list(self.tx_hashes))
            + write_bytes(self.multisig.encode())
        )

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        r = Reader(data)
        header = BlockHeader.decode(r.bytes_())
        tx_hashes = tuple(r.bytes_list())
        multisig = MultiSig.decode(r.bytes_())
        r.assert_eof()
        return cls(header, tx_hashes, multisig)

    def hash(self) -> bytes:
        return self.header.hash()


# header creation and execute_block's header check both derive the merkle
# root over the same tx-hash list a few milliseconds apart; the pairwise
# keccak tree is ~15ms at 10k txs, so memo the last few (FIFO like the
# emulate memo; hashing the key tuple is ~30x cheaper than the tree)
_MERKLE_MEMO: dict = {}
_MERKLE_MEMO_MAX = 8


def tx_merkle_root(tx_hashes: Sequence[bytes]) -> bytes:
    key = tuple(tx_hashes)
    root = _MERKLE_MEMO.get(key)
    if root is None:
        root = merkle_root(list(key)) or ZERO_HASH
        _MERKLE_MEMO[key] = root
        while len(_MERKLE_MEMO) > _MERKLE_MEMO_MAX:
            _MERKLE_MEMO.pop(next(iter(_MERKLE_MEMO)))
    return root
