"""ValidatorManager: the consensus key set for an era, read from chain state.

Parity with the reference's ValidatorManager
(/root/reference/src/Lachain.Core/Blockchain/Validators/ValidatorManager.cs:
25-60): the validator set for era E is whatever the `validators/current`
entry held in the state snapshot of block E-1 (written by the governance
contract's FinishCycle — core/system_contracts.py), cached per era; the
genesis key set applies until the first rotation lands.
"""
from __future__ import annotations

from typing import Dict

from ..consensus.keys import PublicConsensusKeys
from ..storage.state import StateManager


class ValidatorManager:
    def __init__(self, state: StateManager, genesis_keys: PublicConsensusKeys):
        self._state = state
        self.genesis_keys = genesis_keys
        self._cache: Dict[int, PublicConsensusKeys] = {}
        self._decoded: Dict[bytes, PublicConsensusKeys] = {}

    def keys_for_era(self, era: int) -> PublicConsensusKeys:
        """Key set governing era `era` (block height `era`). Requires block
        era-1 to be persisted; falls back to the genesis set before any
        rotation (or for era 0)."""
        if era in self._cache:
            return self._cache[era]
        if era <= 0:
            return self.genesis_keys
        roots = self._state.roots_at(era - 1)
        if roots is None:
            # barrier not met — the caller (era loop / synchronizer) only
            # asks after block era-1 persisted; default to genesis rather
            # than raise so observers can bootstrap
            return self.genesis_keys
        snap = self._state.new_snapshot(roots)
        raw = snap.get("validators", b"current")
        if raw is None:
            keys = self.genesis_keys
        else:
            # one decoded object per distinct set, so consecutive eras under
            # the same set share identity (cheap change detection upstream)
            keys = self._decoded.get(raw)
            if keys is None:
                keys = PublicConsensusKeys.decode(raw)
                self._decoded[raw] = keys
        self._cache[era] = keys
        if len(self._cache) > 64:
            self._cache.pop(min(self._cache))
        return keys
