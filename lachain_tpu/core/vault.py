"""Private wallet: encrypted on-disk key store with era-indexed threshold keys.

Parity with the reference's vault
(/root/reference/src/Lachain.Core/Vault/PrivateWallet.cs): an AES-GCM
encrypted JSON file holding the node's ECDSA identity plus TPKE/TS key
shares keyed by the era they became valid — looked up by predecessor
search (PrivateWallet.cs:63-108, 191-202), so the share dealt at cycle
boundary era E serves every era until the next rotation.

The file key is derived with PBKDF2-HMAC-SHA256 (the reference derives
from the config password the same way via its crypto provider).
"""
from __future__ import annotations

import base64
import bisect
import hashlib
import json
import os
import secrets
from typing import Dict, List, Optional, Tuple

from ..consensus.keys import PrivateConsensusKeys
from ..crypto import ecdsa
from ..crypto import threshold_sig as ts
from ..crypto import tpke

PBKDF2_ITERS = 100_000


def _derive_key(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, PBKDF2_ITERS, dklen=32
    )


class PrivateWallet:
    def __init__(
        self,
        path: Optional[str] = None,
        password: str = "",
        *,
        ecdsa_priv: Optional[bytes] = None,
    ):
        self.path = path
        self._password = password
        self.ecdsa_priv = ecdsa_priv or ecdsa.generate_private_key()
        # era -> key share (sorted era index maintained on insert)
        self._tpke: Dict[int, tpke.TpkePrivateKey] = {}
        self._ts: Dict[int, ts.TsPrivateKeyShare] = {}
        self._eras: List[int] = []

    @property
    def public_key(self) -> bytes:
        return ecdsa.public_key_bytes(self.ecdsa_priv)

    # -- era-keyed shares (predecessor lookup) -----------------------------

    def add_threshold_keys(
        self,
        era: int,
        tpke_priv: tpke.TpkePrivateKey,
        ts_share: ts.TsPrivateKeyShare,
    ) -> None:
        """Register the shares valid FROM `era` (reference
        AddThresholdSignatureKeyAfterBlock / AddTpkePrivateKeyAfterBlock)."""
        self._tpke[era] = tpke_priv
        self._ts[era] = ts_share
        if era not in self._eras:
            bisect.insort(self._eras, era)
        if self.path:
            self.save()

    def _predecessor_era(self, era: int) -> Optional[int]:
        i = bisect.bisect_right(self._eras, era)
        return self._eras[i - 1] if i else None

    def threshold_keys_for_era(
        self, era: int
    ) -> Optional[Tuple[tpke.TpkePrivateKey, ts.TsPrivateKeyShare]]:
        e = self._predecessor_era(era)
        if e is None:
            return None
        return self._tpke[e], self._ts[e]

    def has_keys_for_era(self, era: int) -> bool:
        return self._predecessor_era(era) is not None

    def consensus_keys_for_era(self, era: int) -> Optional[PrivateConsensusKeys]:
        pair = self.threshold_keys_for_era(era)
        if pair is None:
            return None
        return PrivateConsensusKeys(
            tpke_priv=pair[0], ts_share=pair[1], ecdsa_priv=self.ecdsa_priv
        )

    def set_password(self, password: str) -> None:
        """Re-key the wallet (operator `encrypt` verb)."""
        self._password = password

    def to_json(self) -> str:
        """Decrypted payload as JSON (operator `decrypt` verb)."""
        return json.dumps(self._payload(), indent=2)

    # -- persistence -------------------------------------------------------

    def _payload(self) -> dict:
        b64 = lambda b: base64.b64encode(b).decode()
        return {
            "ecdsa": b64(self.ecdsa_priv),
            "tpke": {str(e): b64(k.to_bytes()) for e, k in self._tpke.items()},
            "ts": {str(e): b64(k.to_bytes()) for e, k in self._ts.items()},
        }

    def save(self, path: Optional[str] = None) -> None:
        path = path or self.path
        if path is None:
            raise ValueError("wallet has no path")
        plaintext = json.dumps(self._payload()).encode()
        salt = secrets.token_bytes(16)
        key = _derive_key(self._password, salt)
        blob = ecdsa.aes_gcm_encrypt(key, plaintext)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(b"LTPUWLT1" + salt + blob)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, password: str = "") -> "PrivateWallet":
        with open(path, "rb") as f:
            raw = f.read()
        if raw[:8] != b"LTPUWLT1":
            raise ValueError("not a wallet file")
        salt, blob = raw[8:24], raw[24:]
        key = _derive_key(password, salt)
        plaintext = ecdsa.aes_gcm_decrypt(key, blob)
        data = json.loads(plaintext)
        b64d = base64.b64decode
        w = cls(path=path, password=password, ecdsa_priv=b64d(data["ecdsa"]))
        for e_str, enc in data["tpke"].items():
            w._tpke[int(e_str)] = tpke.TpkePrivateKey.from_bytes(b64d(enc))
        for e_str, enc in data["ts"].items():
            w._ts[int(e_str)] = ts.TsPrivateKeyShare.from_bytes(b64d(enc))
        w._eras = sorted(set(w._tpke) | set(w._ts))
        return w
