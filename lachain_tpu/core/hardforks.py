"""Height-gated hardfork flags.

Parity with the reference's HardforkHeights
(/root/reference/src/Lachain.Core/Blockchain/Hardfork/HardforkHeights.cs:
1-164): a fixed set of named protocol changes, each activating at a
configured block height, set ONCE at process start from the config
(Application.cs:112-115) and consulted by consensus-critical code paths.
Every node on a chain must configure identical heights or state hashes
diverge — exactly the reference's operational contract.

Flags defined so far (heights default to 0 = active from genesis):
  strict_share_validation  HoneyBadger verifies decryption shares eagerly
                           below this height and defers to the batched
                           check above it (reference
                           _skipDecryptedShareValidation, HoneyBadger.cs:30)
  boundary_finish_cycle    governance FinishCycle restricted to the cycle's
                           last block (round-2 rotation alignment rule)
  fast_wasm_gas            the round-3 gas-schedule change: translatable
                           WASM bills 200 gas/op (the translated tier's
                           real dispatch speed) instead of the round-2
                           interpreter-rate 2000/op. Below the activation
                           height every instruction bills the old rate —
                           the first REAL height-gated schedule change
                           (the reference gates such repricings the same
                           way, HardforkHeights.cs:1-164)
"""
from __future__ import annotations

from typing import Dict

_DEFAULTS: Dict[str, int] = {
    "strict_share_validation": 0,
    "boundary_finish_cycle": 0,
    "fast_wasm_gas": 0,
}

_heights: Dict[str, int] = dict(_DEFAULTS)
_frozen = False


def set_hardfork_heights(heights: Dict[str, int], *, force: bool = False) -> None:
    """Install configured activation heights (unknown names rejected).
    One-shot per process, like the reference's static initialization."""
    global _frozen
    if _frozen and not force:
        raise RuntimeError("hardfork heights already set")
    for name in heights:
        if name not in _DEFAULTS:
            raise ValueError(f"unknown hardfork flag {name!r}")
    _heights.update(heights)
    _frozen = True


def reset_for_tests() -> None:
    global _frozen
    _heights.clear()
    _heights.update(_DEFAULTS)
    _frozen = False


def is_active(name: str, height: int) -> bool:
    return height >= _heights[name]


def activation_height(name: str) -> int:
    return _heights[name]
