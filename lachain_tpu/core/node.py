"""A full networked node: consensus over TCP, pool gossip, era lifecycle.

Parity with the reference's node wiring
(/root/reference/src/Lachain.Core/Consensus/ConsensusManager.cs:191-360 era
loop + Application.Start:67-198 service composition): each validator runs a
NetworkManager (signed batches over the TCP hub), an EraRouter per era, a
TransactionPool with gossip (BroadcastLocalTransaction role,
NetworkManagerBase.cs:198-201), and produces blocks through RootProtocol.

The consensus data plane (batched share verification) still runs through
the JAX provider underneath the crypto layer; this module is host runtime.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from ..consensus import messages as M
from ..consensus.era import EraRouter
from ..consensus.keys import PrivateConsensusKeys, PublicConsensusKeys
from ..consensus.root_protocol import RootProtocol
from ..network import wire
from ..network.hub import PeerAddress
from ..network.manager import NetworkManager
from ..storage.kv import KVStore, MemoryKV
from ..storage.state import StateManager
from .block_manager import BlockManager
from .block_producer import BlockProducer
from .execution import TransactionExecuter, get_nonce
from .synchronizer import BlockSynchronizer
from .tx_pool import TransactionPool
from .types import Block, SignedTransaction

logger = logging.getLogger(__name__)


class Node:
    """One validator/observer process."""

    def __init__(
        self,
        *,
        index: int,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        chain_id: int,
        kv: Optional[KVStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        txs_per_block: int = 1000,
        initial_balances: Optional[Dict[bytes, int]] = None,
        flush_interval: float = 0.02,
        executer: Optional[TransactionExecuter] = None,
    ):
        self.index = index
        self.public_keys = public_keys
        self.private_keys = private_keys
        self.chain_id = chain_id
        self.kv = kv if kv is not None else MemoryKV()
        self.state = StateManager(self.kv)
        from . import system_contracts

        self.block_manager = BlockManager(
            self.kv,
            self.state,
            executer or system_contracts.make_executer(chain_id),
        )
        self.block_manager.build_genesis(dict(initial_balances or {}), chain_id)
        self.pool = TransactionPool(
            self.kv, chain_id, account_nonce=self._account_nonce
        )
        self.producer = BlockProducer(
            self.block_manager, self.pool, public_keys.n, txs_per_block
        )
        self.network = NetworkManager(
            private_keys.ecdsa_priv, host, port, flush_interval=flush_interval
        )
        self.network.on_consensus = self._on_consensus
        self.network.on_sync_pool_reply = self._on_pool_txs
        self.network.on_ping_request = self._on_ping_request
        self.synchronizer = BlockSynchronizer(
            self.block_manager, self.pool, self.network, public_keys
        )
        # validator index <-> transport identity
        self._pub_by_index: Dict[int, bytes] = {
            i: pk for i, pk in enumerate(public_keys.ecdsa_pub_keys)
        }
        self._index_by_pub: Dict[bytes, int] = {
            pk: i for i, pk in self._pub_by_index.items()
        }
        self.router: Optional[EraRouter] = None
        self._era_done = asyncio.Event()
        self._stopping = False

    # -- service lifecycle --------------------------------------------------

    async def start(self, first_era: int = 1) -> None:
        await self.network.start()
        # the router exists before the era loop runs so consensus traffic
        # from faster peers is dispatched (or era-buffered), not dropped
        # (observers — index < 0 — only sync, never vote)
        if self.index >= 0:
            self._ensure_router(first_era)
        self.synchronizer.start()

    async def stop(self) -> None:
        self._stopping = True
        await self.synchronizer.stop()
        await self.network.stop()

    @property
    def address(self) -> PeerAddress:
        return self.network.address

    def connect(self, peers: List[PeerAddress]) -> None:
        for p in peers:
            self.network.add_peer(p)

    def _account_nonce(self, addr: bytes) -> int:
        return get_nonce(self.state.new_snapshot(), addr)

    # -- tx ingress + gossip -----------------------------------------------

    def submit_tx(self, stx: SignedTransaction) -> bool:
        ok = self.pool.add(stx)
        if ok:
            self.network.broadcast(wire.sync_pool_reply([stx]))
        return ok

    def _on_pool_txs(self, sender: bytes, txs: List[SignedTransaction]) -> None:
        for stx in txs:
            self.pool.add(stx)

    def _on_ping_request(self, sender: bytes, height: int) -> None:
        self.network.send_to(
            sender, wire.ping_reply(self.block_manager.current_height())
        )

    # -- consensus plumbing -------------------------------------------------

    def _transport_send(self, target: Optional[int], payload) -> None:
        """EraRouter outbound: serialize + enqueue on peer workers; self
        delivery is deferred onto the event loop to keep dispatch
        non-reentrant (the reference's per-protocol queues give the same
        guarantee)."""
        assert self.router is not None
        msg = wire.consensus_msg(self.router.era, payload)
        loop = asyncio.get_running_loop()
        if target is None:
            self.network.broadcast(msg)
            loop.call_soon(self._dispatch_local, self.router.era, payload)
        elif target == self.index:
            loop.call_soon(self._dispatch_local, self.router.era, payload)
        else:
            pub = self._pub_by_index.get(target)
            if pub is not None:
                self.network.send_to(pub, msg)

    def _dispatch_local(self, era: int, payload) -> None:
        if self.router is None or self._stopping:
            return
        self.router.dispatch_external(self.index, payload)
        self._check_era_done()

    def _on_consensus(self, sender_pub: bytes, era: int, payload) -> None:
        sender = self._index_by_pub.get(sender_pub)
        if sender is None:
            logger.warning("consensus message from non-validator dropped")
            return
        if self.router is None:
            return
        self.router.dispatch_external(sender, payload)
        self._check_era_done()

    def _check_era_done(self) -> None:
        if self.router is None:
            return
        pid = M.RootProtocolId(era=self.router.era)
        if self.router.result_of(pid) is not None:
            self._era_done.set()

    def _root_factory(self, pid, router) -> RootProtocol:
        return RootProtocol(
            pid,
            router,
            producer=self.producer,
            ecdsa_priv=self.private_keys.ecdsa_priv,
            ecdsa_pubs=self.public_keys.ecdsa_pub_keys,
        )

    # -- era loop (ConsensusManager.Run) ------------------------------------

    def _ensure_router(self, era: int) -> EraRouter:
        if self.router is None:
            self.router = EraRouter(
                era,
                self.index,
                self.public_keys,
                self.private_keys,
                self._transport_send,
                extra_factories={M.RootProtocolId: self._root_factory},
            )
        else:
            self.router.advance_era(era)
        return self.router

    async def run_era(self, era: int, timeout: float = 120.0) -> Block:
        """Run one era to completion; returns the produced block."""
        router = self._ensure_router(era)
        self._era_done.clear()
        pid = M.RootProtocolId(era=era)
        router.internal_request(
            M.Request(from_id=None, to_id=pid, input=None)
        )
        self._check_era_done()
        while router.result_of(pid) is None:
            self._era_done.clear()
            await asyncio.wait_for(self._era_done.wait(), timeout=timeout)
        block = router.result_of(pid)
        return block

    async def run_eras(self, first: int, count: int) -> List[Block]:
        return [await self.run_era(first + i) for i in range(count)]
