"""A full networked node: consensus over TCP, pool gossip, era lifecycle.

Parity with the reference's node wiring
(/root/reference/src/Lachain.Core/Consensus/ConsensusManager.cs:191-360 era
loop + Application.Start:67-198 service composition): each validator runs a
NetworkManager (signed batches over the TCP hub), an EraRouter per era, a
TransactionPool with gossip (BroadcastLocalTransaction role,
NetworkManagerBase.cs:198-201), and produces blocks through RootProtocol.

The consensus data plane (batched share verification) still runs through
the JAX provider underneath the crypto layer; this module is host runtime.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

from ..consensus import messages as M
from ..consensus.era import EraRouter
from ..consensus.keys import PrivateConsensusKeys, PublicConsensusKeys
from ..consensus.root_protocol import RootProtocol
from ..crypto import ecdsa
from ..network import wire
from ..network.hub import PeerAddress
from ..network.manager import NetworkManager
from ..storage.kv import EntryPrefix, KVStore, MemoryKV, prefixed
from ..storage.state import StateManager
from .block_manager import BlockManager
from .block_producer import BlockProducer
from .execution import TransactionExecuter, get_nonce
from .keygen_manager import KeyGenManager
from .synchronizer import BlockSynchronizer
from .tx_pool import TransactionPool
from .types import (
    Block,
    SignedTransaction,
    Transaction,
    sign_transaction,
    warm_sender_caches,
)
from .validator_manager import ValidatorManager
from .validator_status import ValidatorStatusManager
from .vault import PrivateWallet

logger = logging.getLogger(__name__)


class Node:
    """One validator/observer process."""

    def __init__(
        self,
        *,
        index: int,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        chain_id: int,
        kv: Optional[KVStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        txs_per_block: int = 1000,
        initial_balances: Optional[Dict[bytes, int]] = None,
        flush_interval: float = 0.02,
        executer: Optional[TransactionExecuter] = None,
        wallet: Optional[PrivateWallet] = None,
        block_interval: float = 0.0,
        advertise_host: Optional[str] = None,
        relay=None,  # "host:port:pubhex" or a list of them — NAT'd mode
        pipeline_window: int = 0,
        exec_lanes: int = 0,
        merkle_workers: int = 0,
    ):
        self.index = index
        # era-pipelining lookahead (config blockchain.pipelineWindow). On a
        # TCP node the window widens message acceptance and journal/GC
        # retention so pipelining peers (and the in-process devnet
        # scheduler) interoperate; the windowed front/tail overlap itself
        # is driven by the in-process scheduler (core/devnet.py).
        self.pipeline_window = max(int(pipeline_window), 0)
        self.public_keys = public_keys
        self.private_keys = private_keys
        self.chain_id = chain_id
        self.kv = kv if kv is not None else MemoryKV()
        # invariant scan BEFORE any subsystem reads the db: repairs the
        # safely-repairable torn states a crash can leave (orphan block
        # above tip, stale journal eras, undecodable pool rows) and
        # REFUSES to run on anything else — FsckError carries the report
        # (storage/fsck.py; DEPLOY.md "Crash recovery")
        from ..storage.fsck import FsckError, fsck

        self.fsck_report = fsck(self.kv, repair=True)
        if self.fsck_report.fatal:
            raise FsckError(self.fsck_report)
        self.state = StateManager(self.kv)
        from . import system_contracts

        self.block_manager = BlockManager(
            self.kv,
            self.state,
            executer or system_contracts.make_executer(chain_id),
            lanes=exec_lanes,
        )
        # parallel-merkleization knob (config execution.merkleWorkers):
        # rides the shared trie handle so every freeze/commit sees it
        self.state.trie.merkle_workers = merkle_workers
        self.block_manager.build_genesis(
            dict(initial_balances or {}),
            chain_id,
            validator_pubs=list(public_keys.ecdsa_pub_keys),
        )
        self.pool = TransactionPool(
            self.kv, chain_id, account_nonce=self._account_nonce
        )
        # crash-restore: repopulate from the persisted pool repository (the
        # repository existed but was never replayed on open — a restart
        # silently lost every pending tx)
        restored = self.pool.restore()
        if restored:
            logger.info("restored %d pooled txs from disk", restored)
        # durable consensus send journal (consensus/journal.py): recovery
        # state re-armed in start(), rejoin requests sent in connect()
        from ..consensus.journal import ConsensusJournal

        self.journal = ConsensusJournal(self.kv)
        # durable Byzantine evidence (consensus/evidence.py): persisted on
        # the node KV before any counter publishes, queryable via
        # la_getEvidence, survives restart (fsck checks the records)
        from ..consensus.evidence import EvidenceStore

        self.evidence = EvidenceStore(self.kv)
        self._rejoin_eras: List[int] = []
        self.producer = BlockProducer(
            self.block_manager,
            self.pool,
            public_keys.n,
            txs_per_block,
            proposal_seed=max(index, 0),
        )
        self.network = NetworkManager(
            private_keys.ecdsa_priv,
            host,
            port,
            flush_interval=flush_interval,
            advertise_host=advertise_host,
        )
        self._relay_spec = relay
        self.network.on_consensus = self._on_consensus
        self.network.on_sync_pool_reply = self._on_pool_txs
        self.network.on_ping_request = self._on_ping_request
        self.network.on_message_request = self._on_message_request
        # retransmission/recovery tuning (tests shrink these): the watchdog
        # sweeps every watchdog_interval; a protocol quiet for stall_timeout
        # escalates stall report -> outbox re-request -> forced reconnect
        self.watchdog_interval = 10.0
        self.stall_timeout = 60.0
        # WAN degradation: the ladder above is tuned for loopback; the
        # EFFECTIVE stall timeout stretches with observed fleet RTT
        # (network/rtt.py scale(): never below stall_timeout, capped at
        # 4x) so a 200 ms-RTT fleet degrades gracefully instead of
        # escalating to reconnect thrash on a loopback schedule
        # serving side: one outbox replay per (peer, era) per window, so a
        # hammering (or byzantine) requester cannot turn recovery into an
        # amplification attack
        self.replay_min_interval = 2.0
        # outbox replay batch cap, RTT-scaled upward on slow fleets (a
        # distant requester waits longer between requests, so each round
        # must carry more)
        self.replay_batch_limit = 512
        self._replay_served_at: Dict[tuple, float] = {}
        # native-engine stall detector state: (last_state_string, since, strikes)
        self._native_watch: tuple = ("", 0.0, 0)
        # health/SLO surface: last-commit clocks (monotonic for age math,
        # wall for display) seeded at boot so tip age counts from startup,
        # plus the highest watchdog escalation stage seen since the last
        # persisted block — forward progress clears the strike memory
        self._last_commit_mono = time.monotonic()
        self._last_commit_wall = time.time()
        self._stall_stage = 0
        # idle-anatomy alert (observability.idleAlertFraction): when set,
        # a rolling era idle fraction above it reads degraded on /healthz
        self.idle_alert_fraction: Optional[float] = None
        self.validator_manager = ValidatorManager(self.state, public_keys)
        from .fast_sync import FastSynchronizer

        # serving + client side of trie-level fast state sync; every node
        # serves (reference: peers answer state download RPCs)
        self.fast_sync = FastSynchronizer(self)
        self.synchronizer = BlockSynchronizer(
            self.block_manager,
            self.pool,
            self.network,
            public_keys,
            keys_provider=self.validator_manager.keys_for_era,
        )
        # validator index <-> transport identity
        self._pub_by_index: Dict[int, bytes] = {
            i: pk for i, pk in enumerate(public_keys.ecdsa_pub_keys)
        }
        self._index_by_pub: Dict[bytes, int] = {
            pk: i for i, pk in self._pub_by_index.items()
        }
        self.router: Optional[EraRouter] = None
        self._era_done = asyncio.Event()
        self._stopping = False
        # (sender pubkey) -> [(era, payload)]: future-era consensus traffic
        self._future_msgs: Dict[bytes, list] = {}
        # -- autonomous lifecycle services (reference Application.Start
        #    wiring: KeyGenManager + ValidatorStatusManager hooked on block
        #    persistence; PrivateWallet holds era-keyed threshold keys) -----
        self.wallet = wallet or PrivateWallet(
            ecdsa_priv=private_keys.ecdsa_priv
        )
        self._genesis_private = private_keys
        self.ecdsa_pub = ecdsa.public_key_bytes(private_keys.ecdsa_priv)
        self.address20 = ecdsa.address_from_public_key(self.ecdsa_pub)
        self.keygen_manager = KeyGenManager(
            private_keys.ecdsa_priv,
            self._send_system_tx,
            on_keys=self._install_rotated_keys,
            kv=self.kv,
        )
        self.validator_status = ValidatorStatusManager(
            private_keys.ecdsa_priv,
            self._send_system_tx,
            # everyone who co-signed during that cycle — keyed by recorded
            # pubkeys, not the CURRENT set, so rotated-out validators'
            # attendance still gets reported
            attendance_reader=lambda cycle: self.attendance.counts_for(
                cycle
            ),
        )
        # per-cycle signed-header attendance, durable across restarts
        # (reference: ValidatorAttendance persisted from RootProtocol
        # signed headers, RootProtocol.cs:302-303 +
        # ValidatorAttendanceRepository)
        from ..consensus.attendance import ValidatorAttendance
        from . import system_contracts as _sc

        att_raw = self.kv.get(prefixed(EntryPrefix.VALIDATOR_ATTENDANCE))
        cur_cycle = self.block_manager.current_height() // _sc.CYCLE_DURATION
        if att_raw is not None:
            try:
                self.attendance = ValidatorAttendance.from_bytes(
                    att_raw, cur_cycle, current_as_next=False
                )
            except Exception:
                self.attendance = ValidatorAttendance(cur_cycle)
        else:
            self.attendance = ValidatorAttendance(cur_cycle)
        self.block_manager.on_block_persisted.append(self._on_block_persisted)
        self._height_event = asyncio.Event()
        # target era pacing for the autonomous loop (reference
        # TargetBlockTime, ConsensusManager.cs:78 — default 5000 ms there;
        # 0 = as fast as consensus completes, used by tests)
        self.block_interval = block_interval

    # -- service lifecycle --------------------------------------------------

    async def start(
        self, first_era: int = 1, *, start_synchronizer: bool = True
    ) -> None:
        """With start_synchronizer=False only the network comes up — the
        reference's fast-sync window (Application.Start runs
        FastSynchronizerBatch BEFORE blockSynchronizer.Start, so replay
        doesn't race the state download); call start_services() after."""
        await self.network.start()
        if self._relay_spec:
            # NAT'd mode (reference HubConnector bootstrap): register with
            # the configured relay(s); our gossip address becomes the relay
            # sentinel so peers route to us through it. A list enables
            # failover to the next relay when the current one goes dark.
            from ..network.hub import PeerAddress as _PA

            specs = (
                self._relay_spec
                if isinstance(self._relay_spec, (list, tuple))
                else [self._relay_spec]
            )
            relays = []
            for spec in specs:
                rhost, rport, rpub = spec.rsplit(":", 2)
                relays.append(
                    _PA(
                        public_key=bytes.fromhex(rpub),
                        host=rhost,
                        port=int(rport),
                    )
                )
            self.network.use_relay(relays)
        # the router exists before the era loop runs so consensus traffic
        # from faster peers is dispatched (or era-buffered), not dropped
        # (observers — index < 0 — only sync, never vote)
        if self.index >= 0:
            self._ensure_router(first_era)
            self._recover_journal()
        if start_synchronizer:
            self.start_services()

    def _recover_journal(self) -> None:
        """Crash-recovery replay (journal.py docstring): prune entries for
        eras already settled on-chain, re-arm the router's sent-latches and
        outbox from what remains, and remember the in-flight eras so
        connect() can rejoin them via message_request. Nothing is
        transmitted here — no peer workers exist yet."""
        assert self.router is not None
        height = self.block_manager.current_height()
        self.journal.prune_below(height + 1)
        eras = set()
        n = 0
        for era, _seq, target, data in self.journal.entries():
            self.router.rearm_sent(era, target, data)
            eras.add(era)
            n += 1
        self._rejoin_eras = sorted(eras)
        if n:
            logger.info(
                "journal recovery: re-armed %d sends across eras %s",
                n,
                self._rejoin_eras,
            )

    def start_services(self) -> None:
        self.synchronizer.start()
        self._watchdog_task = asyncio.get_running_loop().create_task(
            self._protocol_watchdog()
        )
        # TPU backends: precompile the era-kernel shapes for this validator
        # set in the background so the first eras don't stall on Mosaic
        # compiles (35-110 s/shape; crypto/warmup.py). Host backends: no-op.
        try:
            from ..crypto.warmup import warmup_era_kernels

            self._warmup_thread = warmup_era_kernels(self.public_keys.n)
        except Exception:  # pragma: no cover - warmup must never block start
            logger.exception("kernel warmup failed to start")
            self._warmup_thread = None

    @property
    def effective_stall_timeout(self) -> float:
        """The watchdog's stall threshold, stretched with observed fleet
        RTT: base stall_timeout on fast links, up to 4x on slow ones
        (RttTracker.scale). Adaptivity widens patience; it never disables
        the ladder."""
        return self.network.rtt.scale(self.stall_timeout)

    async def _protocol_watchdog(self) -> None:
        """Protocol stall watchdog with last-message breadcrumb (reference
        AbstractProtocol 'taking too long' warnings, AbstractProtocol.cs:
        113-135) — escalating instead of merely reporting. Consensus never
        retransmits, so a stall that outlives one report is most likely a
        LOST message, not a slow peer: the second strike re-requests the
        era's traffic from every live peer (outbox replay), the third also
        forces the transport to drop cached sockets and re-dial."""
        import time as _time

        while not self._stopping:
            await asyncio.sleep(self.watchdog_interval)
            router = self.router
            if router is None:
                continue
            now = _time.monotonic()
            # natively-owned protocols have no python instance in
            # router._protocols — their only stall signal is the engine's
            # debug state; snapshot it once per sweep so every stall report
            # this sweep can name the engine side too
            native_state = ""
            nstate_fn = getattr(router, "native_state", None)
            if nstate_fn is not None:
                try:
                    native_state = nstate_fn()
                except Exception:  # engine may be torn down mid-sweep
                    native_state = "<unavailable>"
            # aggregate the ladder per era: one sweep re-requests/reconnects
            # once, however many of the era's protocols are stalled
            stall_after = self.effective_stall_timeout
            era_stage: Dict[int, int] = {}
            for pid, proto in list(router._protocols.items()):
                if proto.terminated or proto.result is not None:
                    continue
                stalled = now - proto.last_activity
                if stalled > stall_after:
                    from ..utils import tracing

                    stage = proto.record_stall()
                    logger.warning(
                        "protocol %s stalled for %.0fs (alive %.0fs, "
                        "strike %d, last message: %s, open spans: %s%s)",
                        pid,
                        stalled,
                        now - proto.started_at,
                        stage,
                        proto.last_message,
                        tracing.open_stack_str(),
                        f", native engine: {native_state}"
                        if native_state
                        else "",
                    )
                    tracing.instant(
                        "watchdog_stall",
                        cat="watchdog",
                        pid=str(pid),
                        stalled_s=round(stalled, 1),
                        stage=stage,
                        last_message=proto.last_message,
                        native_state=native_state,
                    )
                    proto.last_activity = now  # re-arm, don't spam
                    era = getattr(pid, "era", router.era)
                    era_stage[era] = max(era_stage.get(era, 0), stage)
            if nstate_fn is not None:
                stage = self._check_native_stall(router, native_state, now)
                if stage:
                    era_stage[router.era] = max(
                        era_stage.get(router.era, 0), stage
                    )
            for era, stage in era_stage.items():
                self._escalate_stall(era, stage)

    def _check_native_stall(self, router, native_state: str, now) -> int:
        """Stall detection for engine-hosted protocols: no python instance
        means no last_activity to age, so a natively-owned protocol id
        stalls silently unless the engine's debug state is watched. The
        state string encodes per-protocol progress (queue depths, epochs,
        inflight slots), so 'unchanged for stall_timeout while the era has
        no result' is the native analogue of a quiet protocol — report it
        naming the engine state and feed the same escalation ladder."""
        prev_state, mark, strikes = self._native_watch
        if native_state != prev_state or not native_state:
            self._native_watch = (native_state, now, 0)
            return 0
        if now - mark <= self.effective_stall_timeout:
            return 0
        # with pipelining the router spans a window of in-flight eras;
        # commits are strictly sequential, so the stuck era is the OLDEST
        # uncommitted one (window_floor), not the newest admitted
        stuck_era = router.era
        if self.pipeline_window > 0:
            stuck_era = getattr(router, "window_floor", router.era)
        if router.result_of(M.RootProtocolId(era=stuck_era)) is not None:
            # era complete on our side; quiet engine state is expected
            self._native_watch = (native_state, now, 0)
            return 0
        from ..utils import tracing

        strikes += 1
        logger.warning(
            "native engine stalled for %.0fs in era %d (strike %d, "
            "engine state: %s)",
            now - mark,
            stuck_era,
            strikes,
            native_state,
        )
        tracing.instant(
            "watchdog_stall",
            cat="watchdog",
            pid=f"native:era{stuck_era}",
            stalled_s=round(now - mark, 1),
            stage=strikes,
            last_message="",
            native_state=native_state,
        )
        self._native_watch = (native_state, now, strikes)  # re-arm
        return strikes

    def _escalate_stall(self, era: int, stage: int) -> None:
        """Stage 2+: ask every live peer to replay its outbox for `era`
        (and replay our own outbox back at them — the loss may have been
        OUR message). Stage 3+: also force the transport to reconnect."""
        from ..utils import metrics

        self._stall_stage = max(self._stall_stage, stage)
        if stage < 2:
            return
        metrics.inc(
            "consensus_stall_escalations_total",
            labels={"stage": str(min(stage, 3))},
        )
        logger.warning(
            "era %d stalled (strike %d): re-requesting consensus traffic "
            "from %d peers",
            era,
            stage,
            len(self.network.peers),
        )
        self.network.broadcast(wire.message_request(era))
        if stage == 2 and self.router is not None and self.router.era == era:
            # push our own outbox once unprompted: the lost message may have
            # been OURS, and a peer wedged badly enough may never get its
            # own re-request out. Later strikes rely on the peers' replies
            # (re-pushing thousands of messages every sweep helps nobody).
            for idx, pub in self._pub_by_index.items():
                if idx == self.index:
                    continue
                for payload in self.router.outbox_payloads(era, idx):
                    self.network.send_to(pub, wire.consensus_msg(era, payload))
        if stage >= 3:
            self.network.reconnect_peers()

    def health(self) -> Dict[str, object]:
        """One-glance health verdict served by `GET /healthz` and
        `la_getHealth`. Three-state so load balancers and fleet dashboards
        can act without parsing the detail fields:

        ok       — committing, peered, no watchdog strikes
        degraded — behind the fleet's median height, peerless, tip older
                   than the (RTT-stretched) effective stall timeout, one
                   stall strike, or (when idle_alert_fraction is
                   configured) the rolling era idle fraction from the
                   flight recorder above it
        stalled  — watchdog escalated (strike >= 2, python or native) or
                   no commit for 2x the effective stall timeout
        """
        now = time.monotonic()
        tip_age = now - self._last_commit_mono
        height = self.block_manager.current_height()
        peer_heights = sorted(self.synchronizer.peer_heights.values())
        median_peer = (
            peer_heights[len(peer_heights) // 2] if peer_heights else height
        )
        lag = max(0, median_peer - height)
        strikes = max(self._stall_stage, self._native_watch[2])
        # peerless is only a symptom when peers are EXPECTED: a
        # single-validator devnet with nobody to dial stays "ok"
        expected_peers = max(0, len(self._pub_by_index) - 1)
        # rolling idle fraction over the last few completed eras in the
        # flight recorder; only computed when the alert is configured
        # (era_report sweeps the span ring — cheap, but not free)
        idle_fraction = None
        idle_alerting = False
        if self.idle_alert_fraction is not None:
            try:
                from ..utils import tracing

                eras = tracing.era_report()["eras"][-3:]
                walls = sum(e["wall_s"] for e in eras)
                if walls > 0:
                    idle_fraction = round(
                        sum(e["idle_s"] for e in eras) / walls, 4
                    )
                    idle_alerting = idle_fraction > self.idle_alert_fraction
            except Exception:
                pass  # a recorder hiccup must never break the probe
        stall_after = self.effective_stall_timeout
        verdict = "ok"
        if (
            lag > 5
            or tip_age > stall_after
            or (expected_peers > 0 and not self.network.peers)
            or strikes == 1
            or idle_alerting
        ):
            verdict = "degraded"
        if strikes >= 2 or tip_age > 2 * stall_after:
            verdict = "stalled"
        return {
            "status": verdict,
            "height": height,
            "era": self.router.era if self.router is not None else None,
            "tipAgeSeconds": round(tip_age, 3),
            "lastCommitUnix": round(self._last_commit_wall, 3),
            "peerCount": len(self.network.peers),
            "poolDepth": len(self.pool),
            "medianPeerHeight": median_peer,
            "commitLagVsPeers": lag,
            "stallStrikes": strikes,
            "idleFraction": idle_fraction,
            # WAN surface: slowest-peer RTT estimate, the RTT-stretched
            # stall threshold in force, and our advertised wire version
            # (fleet dashboards watch the version column during a roll)
            "rttMaxMs": round(self.network.rtt.max_srtt() * 1000.0, 1),
            "stallTimeoutEffective": round(stall_after, 1),
            "wireVersion": self.network.factory.wire_version,
        }

    async def start_rpc(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        api_key: Optional[str] = None,
        auth_pubkey: Optional[str] = None,
    ):
        """Expose the Web3-shaped JSON-RPC surface (reference
        RpcManager.Start, RPC/RpcManager.cs:1-129). Returns the server
        (its .port reflects the bound port). `auth_pubkey` (compressed
        secp256k1 pubkey hex) unlocks the PRIVATE_METHODS family via
        timestamp+signature auth; when None they are refused."""
        from ..rpc import JsonRpcServer, RpcService

        server = JsonRpcServer(
            host, port, api_key=api_key, auth_pubkey=auth_pubkey
        )
        server.register_all(RpcService(self).methods())
        # liveness probes must work without credentials: the server special-
        # cases GET /healthz through this hook before its api-key gate
        server.health_fn = self.health
        await server.start()
        self._rpc_server = server
        return server

    async def stop(self) -> None:
        self._stopping = True
        self._height_event.set()
        if getattr(self, "_watchdog_task", None) is not None:
            self._watchdog_task.cancel()
            self._watchdog_task = None
        if getattr(self, "_rpc_server", None) is not None:
            await self._rpc_server.stop()
            self._rpc_server = None
        await self.synchronizer.stop()
        await self.network.stop()

    @property
    def address(self) -> PeerAddress:
        return self.network.address

    def connect(self, peers: List[PeerAddress]) -> None:
        for p in peers:
            self.network.add_peer(p)
        if self._rejoin_eras:
            # restart rejoin: ask every peer to replay the traffic of the
            # eras we were mid-flight in when we died (the watchdog's
            # escalation ladder is the backstop if this first ask is lost)
            from ..utils import metrics

            for era in self._rejoin_eras:
                self.network.broadcast(wire.message_request(era))
            metrics.inc(
                "consensus_rejoin_requests_total", len(self._rejoin_eras)
            )
            logger.info("rejoin: requested replay for eras %s", self._rejoin_eras)
            self._rejoin_eras = []

    def _account_nonce(self, addr: bytes) -> int:
        return get_nonce(self.state.new_snapshot(), addr)

    # -- tx ingress + gossip -----------------------------------------------

    def submit_tx(self, stx: SignedTransaction) -> bool:
        # tx lifecycle origin stamp: ingress accepted BEFORE pool admission
        # so the submit→pool delta measures admission, not transport
        from ..utils import txtrace

        txtrace.stamp(stx.hash(), "submit")
        ok = self.pool.add(stx)
        if ok:
            self.network.broadcast(wire.sync_pool_reply([stx]))
        return ok

    def _on_pool_txs(self, sender: bytes, txs: List[SignedTransaction]) -> None:
        # gossip batches arrive many-at-once: batch-recover senders, but
        # ONLY for txs that pass the pool's cheap dedup/gas checks first,
        # deduped within the batch itself — a batch repeating one tx (or a
        # re-gossiped batch) must cost hash lookups, not ECDSA recoveries
        seen = set()
        fresh = []
        for stx in txs:
            h = stx.hash()
            if h not in seen and self.pool.precheck(stx):
                seen.add(h)
                fresh.append(stx)
        warm_sender_caches(fresh, self.chain_id)
        for stx in fresh:
            self.pool.add(stx)

    def _on_ping_request(self, sender: bytes, height: int) -> None:
        self.network.send_to(
            sender, wire.ping_reply(self.block_manager.current_height())
        )

    def _on_message_request(self, sender_pub: bytes, era: int) -> None:
        """A peer is missing consensus traffic for `era`: replay our outbox
        to it (reference message-request/resend layer). Served only for eras
        the router still retains — older eras are settled on-chain and the
        requester's recovery path is block sync, which its next height probe
        triggers anyway."""
        import time as _time

        from ..utils import metrics

        if self.router is None:
            return
        sender = self._index_by_pub.get(sender_pub)
        if sender is None or sender == self.index:
            return
        now = _time.monotonic()
        key = (sender_pub, era)
        last = self._replay_served_at.get(key)
        if last is not None and now - last < self.replay_min_interval:
            metrics.inc("consensus_replay_rate_limited_total")
            return
        self._replay_served_at[key] = now
        if len(self._replay_served_at) > 4096:  # spam/memory bound
            self._replay_served_at = {
                k: v
                for k, v in self._replay_served_at.items()
                if now - v < self.replay_min_interval
            }
        # batch cap scales with fleet RTT: a distant requester's next
        # re-request is an RTT away, so each replay round carries more
        # (scale(1.0) is the dimensionless stretch factor: 1x on fast
        # links, up to 4x on slow ones)
        limit = int(self.replay_batch_limit * self.network.rtt.scale(1.0))
        payloads = self.router.outbox_payloads(era, sender)[:limit]
        for payload in payloads:
            self.network.send_to(sender_pub, wire.consensus_msg(era, payload))
        if payloads:
            metrics.inc("consensus_outbox_replayed_total", len(payloads))
            logger.info(
                "replayed %d era-%d messages to %s",
                len(payloads),
                era,
                sender_pub.hex()[:16],
            )

    # -- consensus plumbing -------------------------------------------------

    def _transport_send(self, target: Optional[int], payload) -> None:
        """EraRouter outbound: serialize + enqueue on peer workers; self
        delivery is deferred onto the event loop to keep dispatch
        non-reentrant (the reference's per-protocol queues give the same
        guarantee)."""
        assert self.router is not None
        msg = wire.consensus_msg(self.router.era, payload)
        loop = asyncio.get_running_loop()
        if target is None:
            self.network.broadcast(msg)
            loop.call_soon(self._dispatch_local, self.router.era, payload)
        elif target == self.index:
            loop.call_soon(self._dispatch_local, self.router.era, payload)
        else:
            pub = self._pub_by_index.get(target)
            if pub is not None:
                self.network.send_to(pub, msg)

    def _dispatch_local(self, era: int, payload) -> None:
        if self.router is None or self._stopping:
            return
        self.router.dispatch_external(self.index, payload)
        self._check_era_done()

    def _on_consensus(self, sender_pub: bytes, era: int, payload) -> None:
        # messages for eras ahead of the local router are stashed at the
        # NODE level keyed by transport pubkey: the router's own postponed
        # buffer holds sender INDICES, which become meaningless (and are
        # discarded) when a rotation swaps the validator set mid-boundary.
        # HBBFT has no retransmission, so dropping them could cost quorum.
        if self.router is None or era > self.router.era:
            self._stash_future(sender_pub, era, payload)
            return
        sender = self._index_by_pub.get(sender_pub)
        if sender is None:
            logger.warning("consensus message from non-validator dropped")
            return
        self.router.dispatch_external(sender, payload)
        self._check_era_done()

    _FUTURE_STASH_CAP = 512  # per sender pubkey, across eras
    _FUTURE_STASH_SENDERS = 64  # distinct pubkeys (spam/memory bound)
    _FUTURE_STASH_HORIZON = 16  # eras ahead worth keeping

    def _stash_future(self, sender_pub: bytes, era: int, payload) -> None:
        cur = self.router.era if self.router is not None else 0
        if era > cur + self._FUTURE_STASH_HORIZON:
            return  # absurdly far ahead: spam
        q = self._future_msgs.get(sender_pub)
        if q is None:
            if len(self._future_msgs) >= self._FUTURE_STASH_SENDERS:
                return  # bound the number of distinct (possibly fake) peers
            q = self._future_msgs.setdefault(sender_pub, [])
        if len(q) >= self._FUTURE_STASH_CAP:
            return
        q.append((era, payload))

    def _replay_future(self) -> None:
        """After the router advances/rebuilds, feed it any stashed messages
        for its era, re-attributed under the CURRENT index table; prune
        everything at or below the current era so entries from senders that
        never become validators cannot accumulate."""
        assert self.router is not None
        era = self.router.era
        for pub, q in list(self._future_msgs.items()):
            keep = []
            sender = self._index_by_pub.get(pub)
            for msg_era, payload in q:
                if msg_era < era:
                    continue  # stale
                if msg_era == era:
                    if sender is not None:
                        self.router.dispatch_external(sender, payload)
                    continue  # current-era traffic never outlives this call
                keep.append((msg_era, payload))
            if keep:
                self._future_msgs[pub] = keep
            else:
                self._future_msgs.pop(pub, None)
        self._check_era_done()

    def _check_era_done(self) -> None:
        if self.router is None:
            return
        pid = M.RootProtocolId(era=self.router.era)
        if self.router.result_of(pid) is not None:
            self._era_done.set()

    def _root_factory(self, pid, router) -> RootProtocol:
        return RootProtocol(
            pid,
            router,
            producer=self.producer,
            ecdsa_priv=self.private_keys.ecdsa_priv,
            ecdsa_pubs=self.public_keys.ecdsa_pub_keys,
        )

    # -- era loop (ConsensusManager.Run) ------------------------------------

    def _effective_pipeline_window(self) -> int:
        """The router's acceptance/retention window, widened by one era
        once the slowest peer's RTT crosses 150 ms: on a WAN fleet a fast
        region legitimately runs an era ahead while its traffic is still
        in flight toward us, and a loopback-sized window would drop (or
        stall on) that lead. Widening acceptance is safe — commits stay
        strictly sequential — it only stops distance being mistaken for
        misbehavior."""
        window = self.pipeline_window
        if self.network.rtt.max_srtt() > 0.15:
            window = max(window, 1)
        return window

    def _ensure_router(self, era: int) -> EraRouter:
        window = self._effective_pipeline_window()
        if self.router is None:
            self.router = EraRouter(
                era,
                self.index,
                self.public_keys,
                self.private_keys,
                self._transport_send,
                extra_factories={M.RootProtocolId: self._root_factory},
                journal=self.journal,
                evidence=self.evidence,
            )
            self.router.pipeline_window = window
        else:
            self.router.pipeline_window = window
            self.router.advance_era(era)
        self._replay_future()
        return self.router

    async def run_era(
        self, era: int, timeout: Optional[float] = 120.0
    ) -> Block:
        """Run one era to completion; returns the produced block.

        A synced block at this height supersedes the local consensus run
        (reference ConsensusManager.cs:339-349): the wait also wakes on
        block persistence so a lagging validator cannot wedge on an era the
        network already finished. With a timeout, TimeoutError is raised if
        neither consensus nor sync makes progress in `timeout` seconds
        total; timeout=None (the autonomous loop) waits indefinitely —
        sync supersession is the recovery path there.
        """
        from ..utils import tracing

        router = self._ensure_router(era)
        self._era_done.clear()
        pid = M.RootProtocolId(era=era)
        sid = tracing.begin("era", era=era)
        outcome = "aborted"
        try:
            router.internal_request(
                M.Request(from_id=None, to_id=pid, input=None)
            )
            self._check_era_done()
            loop = asyncio.get_running_loop()
            deadline = None if timeout is None else loop.time() + timeout
            while router.result_of(pid) is None:
                if self._stopping:
                    raise asyncio.CancelledError(
                        f"node stopped during era {era}"
                    )
                if self.block_manager.current_height() >= era:
                    block = self.block_manager.block_by_height(era)
                    assert block is not None
                    outcome = "synced"
                    return block
                remaining = None
                if deadline is not None:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        outcome = "timeout"
                        raise TimeoutError(f"era {era} stalled")
                self._era_done.clear()
                self._height_event.clear()
                done = asyncio.ensure_future(self._era_done.wait())
                height = asyncio.ensure_future(self._height_event.wait())
                try:
                    await asyncio.wait(
                        [done, height],
                        timeout=remaining,
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    for fut in (done, height):
                        fut.cancel()
            block = router.result_of(pid)
            outcome = "consensus"
            return block
        finally:
            # cross-node causality: our era span carries OUR deterministic
            # trace id (what peers saw on our wire trailers) plus every
            # peer id observed inbound this era — the fleet merger joins
            # spans across pid lanes on exactly these ids
            tracing.end(
                sid,
                outcome=outcome,
                trace=wire.era_trace_id(self.network.public_key, era).hex(),
                peer_traces=",".join(self.network.trace_ids_for(era)),
                # WAN context on the era span: the fleet merger's
                # era-latency-vs-RTT curve reads these two together
                rtt_max_ms=round(self.network.rtt.max_srtt() * 1000.0, 1),
            )

    async def run_eras(self, first: int, count: int) -> List[Block]:
        return [await self.run_era(first + i) for i in range(count)]

    # -- autonomous lifecycle (reference ConsensusManager.Run, 191-360) ------

    def _send_system_tx(self, to: bytes, invocation: bytes) -> None:
        """KeyGenManager/ValidatorStatusManager outbound: build, sign, pool
        and gossip a governance/staking transaction from the node's key."""
        # system-contract calls bill the flat base fee only, so a modest
        # limit keeps the up-front balance requirement tiny (a validator
        # with most of its balance staked must still be able to emit
        # lifecycle transactions)
        tx = Transaction(
            to=to,
            value=0,
            nonce=self.pool.next_nonce(self.address20),
            gas_price=1,
            gas_limit=100_000,
            invocation=invocation,
        )
        stx = sign_transaction(tx, self.private_keys.ecdsa_priv, self.chain_id)
        self.submit_tx(stx)

    def _install_rotated_keys(self, first_era, keyring, participants) -> None:
        """DKG finished: stash this node's new shares in the era-keyed
        wallet (reference GovernanceContract.ChangeValidators ->
        PrivateWallet.AddThresholdSignatureKeyAfterBlock)."""
        self.wallet.add_threshold_keys(
            first_era, keyring.tpke_priv, keyring.ts_share
        )
        logger.info(
            "node %d: rotated threshold keys installed from era %d",
            self.index,
            first_era,
        )

    def _on_block_persisted(self, block: Block) -> None:
        from ..utils import tracing

        tracing.instant(
            "block_persisted", cat="block", height=block.header.index
        )
        # a persisted block is the strongest health signal: refresh the
        # tip-age clocks and forgive past watchdog strikes
        self._last_commit_mono = time.monotonic()
        self._last_commit_wall = time.time()
        self._stall_stage = 0
        snap = self.state.new_snapshot()
        self.validator_status.on_block_persisted(block, snap)
        self.keygen_manager.on_block_persisted(block, snap)
        self._record_attendance(block)
        self._height_event.set()

    def _record_attendance(self, block: Block) -> None:
        """Count each multisig signer's co-signature for the block's cycle
        and persist (reference: ValidatorAttendance.IncrementAttendance via
        RootProtocol.cs:302-303, durable in the attendance repository)."""
        from . import system_contracts as _sc

        keys = self.validator_manager.keys_for_era(block.header.index)
        if keys is None:
            return
        cycle = block.header.index // _sc.CYCLE_DURATION
        if cycle > self.attendance.next_cycle:
            from ..consensus.attendance import ValidatorAttendance

            self.attendance = ValidatorAttendance.from_bytes(
                self.attendance.to_bytes(), cycle, current_as_next=False
            )
        for idx, _sig in block.multisig.signatures:
            if 0 <= idx < len(keys.ecdsa_pub_keys):
                self.attendance.increment(keys.ecdsa_pub_keys[idx], cycle)
        self.kv.put(
            prefixed(EntryPrefix.VALIDATOR_ATTENDANCE),
            self.attendance.to_bytes(),
        )

    async def _wait_height(self, height: int) -> None:
        while (
            not self._stopping
            and self.block_manager.current_height() < height
        ):
            self._height_event.clear()
            try:
                await asyncio.wait_for(self._height_event.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass

    def _rekey_for_era(self, era: int) -> Optional[int]:
        """Reconfigure consensus identity for `era` from the era-1 snapshot
        (ValidatorManager) and the wallet's era-keyed shares. Returns this
        node's validator index, or None when it sits this era out."""
        keys = self.validator_manager.keys_for_era(era)
        if keys is not self.public_keys:
            # ValidatorManager returns one stable object per distinct set,
            # so identity comparison is exact change detection
            self.public_keys = keys
            self._pub_by_index = {
                i: pk for i, pk in enumerate(keys.ecdsa_pub_keys)
            }
            self._index_by_pub = {
                pk: i for i, pk in self._pub_by_index.items()
            }
            self.producer.n = keys.n
        try:
            my_index = keys.ecdsa_pub_keys.index(self.ecdsa_pub)
        except ValueError:
            # demoted to observer: drop the stale-era router and identity so
            # inbound messages from the NEW set are never attributed into an
            # OLD-set router (index tables were just rebuilt above)
            self.router = None
            self.index = -1
            return None
        priv = self._private_keys_matching(keys, my_index, era)
        if priv is None:
            logger.warning(
                "node %d: in validator set for era %d but holds no matching "
                "threshold keys — observing",
                self.index,
                era,
            )
            self.router = None
            self.index = -1
            return None
        self.private_keys = priv
        self.index = my_index
        return my_index

    def _private_keys_matching(
        self, keys: PublicConsensusKeys, my_index: int, era: int
    ) -> Optional[PrivateConsensusKeys]:
        """The private share set whose TPKE verification key matches slot
        `my_index` of the era's PUBLIC set. Checking the match (one scalar
        mul) instead of trusting the wallet's era arithmetic protects
        against a rotation whose on-chain flip slipped a cycle: wallet keys
        installed for era E must not be used while an older set still
        governs (reference rescans keys at era start,
        ConsensusManager.cs:250-266)."""
        from ..crypto import bls12381 as bls

        want_vk = keys.tpke_verification_keys[my_index].y_i
        candidates = []
        wallet_keys = self.wallet.consensus_keys_for_era(era)
        if wallet_keys is not None:
            candidates.append(wallet_keys)
        candidates.append(self._genesis_private)
        for cand in candidates:
            if cand.tpke_priv is None or cand.tpke_priv.my_id != my_index:
                continue
            y = bls.g1_mul(bls.G1_GEN, cand.tpke_priv.x_i)
            if bls.g1_to_affine(y) == bls.g1_to_affine(want_vk):
                return cand
        return None

    async def run(self, first_era: int = 1, stop_at: Optional[int] = None) -> None:
        """The autonomous era loop (reference ConsensusManager.Run,
        ConsensusManager.cs:191-360): wait for block era-1, load the era's
        validator set from the era-1 snapshot and the era's keys from the
        wallet, run consensus if a member (sync supersedes a stalled era),
        fire persistence hooks, GC, advance."""
        loop = asyncio.get_running_loop()
        era = first_era
        while not self._stopping and (stop_at is None or era <= stop_at):
            era_start = loop.time()
            await self._wait_height(era - 1)
            if self._stopping:
                return
            my_index = self._rekey_for_era(era)
            if my_index is None:
                await self._wait_height(era)  # observer for this era
            else:
                self._rebuild_router(era)
                await self.run_era(era, timeout=None)
            self._finish_era_metrics(era, loop.time() - era_start)
            if self.block_interval > 0:
                remaining = self.block_interval - (loop.time() - era_start)
                if remaining > 0 and not self._stopping:
                    await asyncio.sleep(remaining)
            era += 1

    def _finish_era_metrics(
        self, era: int, wall_seconds: Optional[float] = None
    ) -> None:
        """Per-era crypto counter dump + reset (reference FinishEra ->
        DefaultCrypto.ResetBenchmark, ConsensusManager.cs:178,
        DefaultCrypto.cs:47-69)."""
        from ..utils import metrics

        if wall_seconds is not None:
            metrics.observe_hist(
                "era_wall_seconds",
                wall_seconds,
                buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
            )
        snap = metrics.timer_snapshot(reset=True, reset_prefix="crypto_")
        crypto = {k: v for k, v in snap.items() if k.startswith("crypto_")}
        if crypto:
            logger.info("era %d crypto benchmark: %s", era, crypto)

    def _rebuild_router(self, era: int) -> None:
        """Router for `era` under the CURRENT key set. Unlike
        _ensure_router, this also swaps identity when rotation changed the
        validator set."""
        if (
            self.router is not None
            and self.router.public_keys is not self.public_keys
        ):
            self.router = None  # key set changed: a fresh router is required
        self._ensure_router(era)
