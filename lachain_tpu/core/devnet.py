"""Single-process multi-validator devnet — the end-to-end slice.

Parity with the reference's 4-node local net (docker-compose.4nodes.yml +
TrustedKeygen, SURVEY.md §4.5) collapsed into one process for tests and the
bench: N validators, each with its own KV store / state / pool / producer,
wired through the deterministic simulator. The era loop plays the role of
ConsensusManager.Run (/root/reference/src/Lachain.Core/Consensus/
ConsensusManager.cs:191-360): start RootProtocol for era E, wait for every
node's block, verify they all committed the same block, advance.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..consensus import messages as M
from ..consensus.keys import trusted_key_gen
from ..consensus.root_protocol import RootProtocol
from ..consensus.simulator import DeliveryMode, SimulatedNetwork
from ..crypto import ecdsa
from ..crypto.hashes import keccak256
from ..storage.kv import EntryPrefix, KVStore, MemoryKV, prefixed
from ..storage.state import StateManager
from ..utils.serialization import write_u64
from . import system_contracts
from .block_manager import BlockManager
from .block_producer import BlockProducer
from .execution import get_balance, get_nonce, set_balance
from .tx_pool import TransactionPool
from .types import (
    ZERO_HASH,
    Block,
    BlockHeader,
    MultiSig,
    SignedTransaction,
)

DEFAULT_CHAIN_ID = 225  # our own chain id


@dataclass
class DevnetNode:
    index: int
    kv: KVStore
    state: StateManager
    block_manager: BlockManager
    pool: TransactionPool
    producer: BlockProducer


class Devnet:
    """N-validator in-process chain with HoneyBadger consensus."""

    def __init__(
        self,
        n: int = 4,
        f: int = 1,
        chain_id: int = DEFAULT_CHAIN_ID,
        seed: int = 0,
        txs_per_block: int = 1000,
        initial_balances: Optional[Dict[bytes, int]] = None,
        mode: DeliveryMode = DeliveryMode.TAKE_FIRST,
        engine: str = "python",
        fault_plan=None,
        max_recovery_rounds: int = 16,
        kv_factory: Optional[Callable[[int], KVStore]] = None,
        pipeline_window: int = 0,
        journals: Optional[List] = None,
        exec_lanes: int = 1,
        merkle_workers: int = 1,
        adversary=None,
        link_shaper=None,
        rbc_batch: bool = False,
    ):
        # link_shaper (network/faults.py LinkShaper): WAN emulation on the
        # simulated delivery layer — per-region-pair latency/jitter/
        # bandwidth in virtual ticks. A convenience over threading a full
        # FaultPlan: wraps into one (or onto the given plan) here.
        if link_shaper is not None:
            import dataclasses as _dc

            from ..network.faults import FaultPlan

            if fault_plan is None:
                fault_plan = FaultPlan(seed=seed, shaper=link_shaper)
            else:
                fault_plan = _dc.replace(fault_plan, shaper=link_shaper)
        self.n, self.f = n, f
        self.chain_id = chain_id
        # pipeline_window > 0 turns run_eras into a windowed scheduler that
        # overlaps era e+1's front (propose/RBC/BA/coin/TPKE) with era e's
        # tail (sign/verify/commit) — native engine only
        self.pipeline_window = max(int(pipeline_window), 0)
        if self.pipeline_window > 0 and engine != "native":
            raise ValueError("era pipelining requires engine='native'")
        rng = random.Random(seed)

        class _Rng:
            def randbelow(self, k):
                return rng.randrange(k)

        self.public_keys, self.private_keys = trusted_key_gen(n, f, rng=_Rng())
        self.initial_balances = dict(initial_balances or {})

        # kv_factory(node_index) -> KVStore lets campaigns run each
        # validator on a DURABLE engine (LsmKV/SqliteKV store per node)
        # instead of the default in-memory store — the state-root identity
        # tests drive the same devnet over both engines this way
        self.nodes: List[DevnetNode] = []
        for i in range(n):
            kv = kv_factory(i) if kv_factory is not None else MemoryKV()
            state = StateManager(kv)
            # full system-contract registry (deploy/LRC-20/governance/staking)
            # so the devnet exercises the same execution surface as a real node
            executer = system_contracts.make_executer(chain_id)
            # exec_lanes=1 keeps devnet harnesses on the serial oracle by
            # default; campaigns opt into lanes explicitly (results are
            # bit-identical either way — core/parallel_exec.py)
            bm = BlockManager(kv, state, executer, lanes=exec_lanes)
            # like exec_lanes: devnet harnesses default to the serial
            # merkle walker; campaigns opt in (roots identical either way)
            state.trie.merkle_workers = merkle_workers
            bm.build_genesis(
                self.initial_balances,
                chain_id,
                validator_pubs=list(self.public_keys.ecdsa_pub_keys),
            )
            pool = TransactionPool(
                kv,
                chain_id,
                account_nonce=self._nonce_reader(state),
            )
            producer = BlockProducer(bm, pool, n, txs_per_block, proposal_seed=i)
            self.nodes.append(
                DevnetNode(
                    index=i,
                    kv=kv,
                    state=state,
                    block_manager=bm,
                    pool=pool,
                    producer=producer,
                )
            )

        def root_factory_for(node: DevnetNode):
            def factory(pid, router):
                return RootProtocol(
                    pid,
                    router,
                    producer=node.producer,
                    ecdsa_priv=self.private_keys[node.index].ecdsa_priv,
                    ecdsa_pubs=self.public_keys.ecdsa_pub_keys,
                )

            return factory

        # one shared simulated network; per-node RootProtocol factories.
        # engine="native" routes the flood protocols through the C++ runtime
        # (consensus/native_rt.py) — same protocols, same crypto, ~100x the
        # dispatch throughput at N=64.
        # fault_plan (network/faults.py FaultPlan) threads through to the
        # delivery layer: chaos tests and the `lachain-tpu chaos` verb run
        # whole eras under seeded loss/partition/crash schedules
        if engine == "native":
            from ..consensus.native_rt import NativeSimulatedNetwork

            net_cls = NativeSimulatedNetwork
            net_kw = dict(
                fault_plan=fault_plan,
                pipeline_window=self.pipeline_window,
                journals=journals,
                use_rbc_batcher=rbc_batch,
            )
        else:
            net_cls = SimulatedNetwork
            net_kw = dict(
                fault_plan=fault_plan,
                max_recovery_rounds=max_recovery_rounds,
                use_rbc_batcher=rbc_batch,
            )
            if journals is not None:
                # the python simulator has no journal hosting; passing one
                # is a real request we cannot honor silently
                raise ValueError(
                    "consensus journals require engine='native'"
                )
        self.net = net_cls(
            self.public_keys,
            self.private_keys,
            era=1,
            seed=seed,
            mode=mode,
            **net_kw,
        )
        for i, router in enumerate(self.net.routers):
            if engine == "native":
                # native engine: hand each validator its block-production
                # context so RootProtocol is hosted natively (an
                # _extra_factories override still forces the Python class)
                self.net.set_root_context(
                    i,
                    self.nodes[i].producer,
                    self.private_keys[i].ecdsa_priv,
                    self.public_keys.ecdsa_pub_keys,
                )
            else:
                router._extra_factories[M.RootProtocolId] = root_factory_for(
                    self.nodes[i]
                )
        # adversary (consensus/adversary.py AdversaryPlan): smart-malicious
        # traitors with real key shares. Installed AFTER root contexts so a
        # native traitor's python-override fallback finds its producer seam.
        self.adversary = adversary
        if adversary is not None:
            from ..consensus.adversary import install as install_adversary

            install_adversary(adversary, self.net)

    @staticmethod
    def _nonce_reader(state: StateManager):
        def read(addr: bytes) -> int:
            return get_nonce(state.new_snapshot(), addr)

        return read

    # -- tx ingress -------------------------------------------------------------
    def submit_tx(self, stx: SignedTransaction, to_node: int = 0) -> bool:
        """Reference path: eth_sendRawTransaction -> TransactionPool.Add; the
        devnet gossips the tx to every node's pool (BroadcastLocalTransaction
        role)."""
        from ..utils import txtrace

        txtrace.stamp(stx.hash(), "submit")
        ok = self.nodes[to_node].pool.add(stx)
        if ok:
            for node in self.nodes:
                if node.index != to_node:
                    node.pool.add(stx)
        return ok

    # -- era loop ----------------------------------------------------------------
    def run_era(self, era: int, max_messages: int = 2_000_000) -> List[Block]:
        """Run one consensus era to completion on every node."""
        from ..utils import tracing

        # the era span is the flight recorder's attribution window: the
        # era report and the clock-alignment tests anchor on it
        with tracing.span("era", era=era):
            for router in self.net.routers:
                router.advance_era(era)
            pid = M.RootProtocolId(era=era)
            for i in range(self.n):
                self.net.post_request(i, pid, None)
            ok = self.net.run(
                lambda: all(
                    r.result_of(pid) is not None for r in self.net.routers
                ),
                max_messages=max_messages,
            )
        if not ok:
            raise RuntimeError(f"era {era} did not complete")
        blocks = [r.result_of(pid) for r in self.net.routers]
        h0 = blocks[0].hash()
        assert all(b.hash() == h0 for b in blocks), "devnet fork!"
        return blocks

    def run_eras(
        self, first: int, count: int, max_messages: int = 2_000_000
    ) -> List[Block]:
        if self.pipeline_window > 0:
            return self._run_eras_pipelined(
                first, count, max_messages=max_messages
            )
        out = []
        for era in range(first, first + count):
            out.append(self.run_era(era, max_messages=max_messages)[0])
        return out

    # -- pipelined era window ---------------------------------------------------
    def _decided_txs(self, era: int) -> List[SignedTransaction]:
        """The tx set era `era`'s block WILL carry, derived from router 0's
        HB result exactly as RootHost.on_sign derives it (the result is
        content-identical at every validator, so router 0 suffices).
        Available at front-complete — before the block itself exists."""
        from .block_producer import decode_tx_batch

        hb_result = self.net.routers[0].hb_host(era).result or {}
        seen = set()
        txs: List[SignedTransaction] = []
        for slot in sorted(hb_result):
            try:
                batch = decode_tx_batch(hb_result[slot])
            except (ValueError, AssertionError):
                continue
            for stx in batch:
                h = stx.hash()
                if h not in seen:
                    seen.add(h)
                    txs.append(stx)
        return txs

    def _run_eras_pipelined(
        self, first: int, count: int, max_messages: int = 2_000_000
    ) -> List[Block]:
        """Windowed era scheduler: era e+1's FRONT (propose/encrypt/RBC/BA/
        coin/TPKE verify-combine, up to the deferred header sign) runs on
        this thread while era e's TAIL (sign/flood/ECDSA-verify/produce/
        commit) runs on a worker thread. Commits stay strictly sequential
        (the tail worker processes eras ascending), so state roots — and
        block hashes — are exactly the sequential run's. At most
        pipeline_window + 1 eras are in flight at once."""
        import queue as queue_mod
        import threading

        from ..utils import metrics, tracing

        window = self.pipeline_window
        eras = list(range(first, first + count))
        self.net.pipeline_begin()
        committed = {e: threading.Event() for e in eras}
        blocks: Dict[int, Block] = {}
        era_spans: Dict[int, int] = {}
        tail_q: "queue_mod.Queue" = queue_mod.Queue()
        tail_err: List[BaseException] = []

        def tail_worker() -> None:
            while True:
                era = tail_q.get()
                if era is None:
                    return
                try:
                    with tracing.span("era.tail", era=era):
                        era_blocks = self.net.run_tail(
                            era, max_messages=max_messages
                        )
                        h0 = era_blocks[0].hash()
                        assert all(
                            b.hash() == h0 for b in era_blocks
                        ), "devnet fork!"
                        self.net.commit_era(era)
                    blocks[era] = era_blocks[0]
                    tracing.end(era_spans[era])
                    committed[era].set()
                except BaseException as exc:  # noqa: BLE001
                    tail_err.append(exc)
                    committed[era].set()  # unblock the scheduler
                    return

        worker = threading.Thread(
            target=tail_worker, name="consensus-tail", daemon=True
        )
        worker.start()
        in_flight: List[int] = []
        try:
            for era in eras:
                # admission: keep at most window fronts ahead of the
                # oldest uncommitted era
                while len(in_flight) > window:
                    committed[in_flight[0]].wait()
                    if tail_err:
                        raise tail_err[0]
                    in_flight.pop(0)
                if tail_err:
                    raise tail_err[0]
                # the "era" span opens at admission and closes at commit
                # (on the tail thread): neighbor eras' spans genuinely
                # overlap, which is what era_report's overlap_s measures
                era_spans[era] = tracing.begin("era", era=era)
                self.net.open_era(era)
                pid = M.RootProtocolId(era=era)
                for i in range(self.n):
                    self.net.post_request(i, pid, None)
                with tracing.span("era.front", era=era):
                    self.net.run_front(era, max_messages=max_messages)
                in_flight.append(era)
                metrics.set_gauge("consensus_pipeline_depth", len(in_flight))
                if era != eras[-1]:
                    # before era+1 proposes: overlay this era's decided tx
                    # set so the next proposal behaves as if the block had
                    # already committed (main thread — the overlay is only
                    # read here, by the next post_request's proposal)
                    txs = self._decided_txs(era)
                    for node in self.nodes:
                        node.producer.pipeline_overlay_push(
                            era, txs, self.chain_id
                        )
                tail_q.put(era)
            for era in in_flight:
                committed[era].wait()
                if tail_err:
                    raise tail_err[0]
        finally:
            tail_q.put(None)
            worker.join(timeout=60)
            metrics.set_gauge("consensus_pipeline_depth", 0)
            for node in self.nodes:
                node.producer.pipeline_overlay_clear()
            self.net.pipeline_end()
        return [blocks[e] for e in eras]

    # -- helpers ------------------------------------------------------------------
    def close(self) -> None:
        """Release per-node stores (no-op for MemoryKV; required for the
        durable engines a kv_factory may supply)."""
        for node in self.nodes:
            node.kv.close()

    def balance(self, addr: bytes, node: int = 0) -> int:
        return get_balance(self.nodes[node].state.new_snapshot(), addr)

    def height(self, node: int = 0) -> int:
        return self.nodes[node].block_manager.current_height()


# -- fast-sync fixtures -------------------------------------------------------
# Deterministic chain fabrication for the state-download tests: a genesis +
# one properly multisigned block whose state trie carries an arbitrary number
# of synthetic accounts. Everything derives from (keys, seed, accounts), so
# the same fixture can be rebuilt bit-identically in another process — the
# real-SIGKILL fast-sync test runs serving validators as subprocesses that
# regenerate the exact same store from the same arguments.


def fixture_account(seed: int, i: int) -> bytes:
    """The i-th synthetic 20-byte address of a fabricated fixture."""
    return keccak256(b"devnet-fixture" + write_u64(seed) + write_u64(i))[:20]


def fabricate_chain_store(
    public_keys,
    private_keys,
    *,
    chain_id: int = DEFAULT_CHAIN_ID,
    accounts: int = 0,
    initial_balances: Optional[Dict[bytes, int]] = None,
    seed: int = 7,
    kv: Optional[KVStore] = None,
):
    """Genesis + a signed block 1 holding `accounts` synthetic balances.

    Returns (kv, block1, roots). The block carries an N-F validator
    multisig over its header, so a fast-syncing observer that knows the
    genesis validator set accepts it without a trusted checkpoint. The
    per-account addresses come from fixture_account(seed, i) — tests can
    spot-check balances without materializing the whole set.
    """
    kv = kv if kv is not None else MemoryKV()
    state = StateManager(kv)
    bm = BlockManager(kv, state, system_contracts.make_executer(chain_id))
    genesis = bm.build_genesis(
        dict(initial_balances or {}),
        chain_id,
        validator_pubs=list(public_keys.ecdsa_pub_keys),
    )
    snap = state.new_snapshot()
    for i in range(accounts):
        set_balance(snap, fixture_account(seed, i), 10_000 + i)
    roots = snap.freeze()
    header = BlockHeader(
        index=1,
        prev_block_hash=genesis.hash(),
        merkle_root=ZERO_HASH,
        state_hash=roots.state_hash(),
        nonce=0,
    )
    hh = header.hash()
    quorum = public_keys.n - public_keys.f
    sigs = tuple(
        (i, ecdsa.sign_hash(private_keys[i].ecdsa_priv, hh))
        for i in range(quorum)
    )
    block = Block(header=header, tx_hashes=(), multisig=MultiSig(sigs))
    kv.write_batch(
        [
            (prefixed(EntryPrefix.BLOCK_BY_HASH, block.hash()), block.encode()),
            (
                prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT, write_u64(1)),
                block.hash(),
            ),
        ]
    )
    state.commit(1, roots)
    return kv, block, roots


def clone_store(src: KVStore, dst: Optional[KVStore] = None) -> KVStore:
    """Copy every row of `src` into `dst` (fresh MemoryKV by default).

    Fabricating a 100k-node fixture once and cloning it into each serving
    validator's store is an order of magnitude cheaper than rebuilding the
    trie per node — and content addressing makes the copies exact replicas.
    """
    dst = dst if dst is not None else MemoryKV()
    dst.ingest(list(src.scan_prefix(b"")))
    return dst


def run_fixture_server(
    *,
    n: int = 4,
    f: int = 1,
    index: int = 0,
    seed: int = 0,
    fixture_seed: int = 7,
    accounts: int = 0,
    chain_id: int = DEFAULT_CHAIN_ID,
    port: int = 0,
) -> None:
    """Subprocess entry point: serve a fabricated chain over real TCP.

    Regenerates the (deterministic) validator keys and fixture store from
    the same arguments the parent test used, starts a full Node on
    127.0.0.1, prints one JSON line {"port": ..., "pub": ...} so the parent
    can connect, then serves until killed — the parent SIGKILLs it
    mid-download to exercise real-process failover.
    """
    import asyncio
    import json
    import sys

    rng = random.Random(seed)

    class _Rng:
        def randbelow(self, k):
            return rng.randrange(k)

    public_keys, private_keys = trusted_key_gen(n, f, rng=_Rng())
    kv, _block, _roots = fabricate_chain_store(
        public_keys,
        private_keys,
        chain_id=chain_id,
        accounts=accounts,
        seed=fixture_seed,
    )

    async def _serve() -> None:
        from .node import Node

        node = Node(
            index=index,
            public_keys=public_keys,
            private_keys=private_keys[index],
            chain_id=chain_id,
            kv=kv,
            port=port,
            flush_interval=0.01,
        )
        # serving throughput is not what the failover tests measure: the
        # default serve throttle would read as timeouts on a hammering
        # observer and get the SURVIVOR declared dead
        node.fast_sync.serve_rate = 1e9
        node.fast_sync.serve_capacity = 1e9
        await node.start(start_synchronizer=False)
        print(
            json.dumps(
                {
                    "port": node.address.port,
                    "pub": node.address.public_key.hex(),
                }
            ),
            flush=True,
        )
        await asyncio.Event().wait()  # serve until the parent kills us

    try:
        asyncio.run(_serve())
    except KeyboardInterrupt:  # pragma: no cover - parent teardown
        sys.exit(0)
