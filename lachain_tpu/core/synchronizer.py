"""Block synchronizer: follow-the-chain sync + multisig quorum verification.

Parity with the reference's sync path
(/root/reference/src/Lachain.Core/Network/BlockSynchronizer.cs:28-236:
PingWorker tracks peer heights, BlockSyncWorker requests block ranges from
the best peer, each block's validator multisig is quorum-checked and then
executed through the exact producer commit path) and MultisigVerifier
(Blockchain/Operations/MultisigVerifier.cs:1-67).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from ..consensus.keys import PublicConsensusKeys
from ..crypto import ecdsa
from ..network import wire
from ..network.manager import NetworkManager
from .block_manager import BlockManager
from .tx_pool import TransactionPool
from .types import Block, SignedTransaction

logger = logging.getLogger(__name__)

MAX_BLOCKS_PER_REQUEST = 32


def verify_block_multisig(
    block: Block, public_keys: PublicConsensusKeys
) -> bool:
    """N-F distinct valid validator signatures over the header hash
    (reference MultisigVerifier.cs:1-67)."""
    header_hash = block.header.hash()
    seen = set()
    valid = 0
    for idx, sig in block.multisig.signatures:
        if idx in seen or not 0 <= idx < public_keys.n:
            continue
        seen.add(idx)
        pub = public_keys.ecdsa_pub_keys[idx]
        if ecdsa.verify_hash(pub, header_hash, sig):
            valid += 1
    return valid >= public_keys.n - public_keys.f


class BlockSynchronizer:
    """Keeps a node's chain caught up with its peers."""

    def __init__(
        self,
        block_manager: BlockManager,
        pool: TransactionPool,
        network: NetworkManager,
        public_keys: PublicConsensusKeys,
        *,
        ping_interval: float = 1.0,
        keys_provider=None,
    ):
        self.bm = block_manager
        self.pool = pool
        self.network = network
        self.public_keys = public_keys
        # height -> PublicConsensusKeys: with on-chain validator rotation the
        # multisig quorum for block H must be checked against the set that
        # governed era H (ValidatorManager role). The default reads
        # self.public_keys dynamically so assigning that attribute stays
        # meaningful for fixed-set users.
        self.keys_provider = keys_provider or (
            lambda height: self.public_keys
        )
        self.ping_interval = ping_interval
        self.peer_heights: Dict[bytes, int] = {}
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        self._new_block = asyncio.Event()
        self._request_inflight = False
        self._request_peer: Optional[bytes] = None
        self._request_start = 0
        self._request_time = 0.0
        # an unanswered request is abandoned after this long so
        # _maybe_request rotates to the next best peer instead of wedging
        # forever (reference BlockSynchronizer re-polls; a single lost reply
        # must not stall sync)
        self.request_timeout = max(3.0, 4 * ping_interval)
        # peers that timed out or served nothing useful are benched for a
        # window; pings keep updating their height but _best_peer skips them.
        # Without this, a ping-responsive but sync-useless top-height peer
        # re-enters the height table ~1s after being dropped and throttles
        # sync to one batch per timeout period (or, for an always-empty
        # replier, spins an unthrottled request/empty-reply hot loop).
        self.peer_cooldown = 4 * self.request_timeout
        self._benched: Dict[bytes, float] = {}
        # wire handlers (the serving side lives here too)
        network.on_ping_reply = self._on_ping_reply
        network.on_sync_blocks_request = self._on_blocks_request
        network.on_sync_blocks_reply = self._on_blocks_reply
        network.on_sync_pool_request = self._on_pool_request

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._ping_loop())]

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    async def _ping_loop(self) -> None:
        while not self._stopped:
            self.network.broadcast(
                wire.ping_request(self.bm.current_height())
            )
            self._maybe_request()
            await asyncio.sleep(self.ping_interval)

    # -- peer state --------------------------------------------------------

    def _on_ping_reply(self, sender: bytes, height: int) -> None:
        self.peer_heights[sender] = height
        self._maybe_request()

    def _best_peer(self) -> Optional[Tuple[bytes, int]]:
        now = asyncio.get_event_loop().time()
        live = [
            (pub, h)
            for pub, h in self.peer_heights.items()
            if self._benched.get(pub, 0.0) <= now
        ]
        if not live:
            return None
        return max(live, key=lambda kv: kv[1])

    def _bench_peer(self, pub: bytes) -> None:
        self._benched[pub] = (
            asyncio.get_event_loop().time() + self.peer_cooldown
        )

    def best_peers(self, k: int = 4) -> List[bytes]:
        """Up to `k` un-benched peers ordered by advertised height (ties
        broken by pubkey for determinism) — the serving-peer candidate
        set for multi-peer fast sync."""
        now = asyncio.get_event_loop().time()
        live = [
            (h, pub)
            for pub, h in self.peer_heights.items()
            if self._benched.get(pub, 0.0) <= now
        ]
        live.sort(key=lambda hv: (-hv[0], hv[1]))
        return [pub for _, pub in live[:k]]

    def _request_timeout_for(self, pub: Optional[bytes]) -> float:
        """Per-request abandon threshold: the fixed request_timeout floor,
        widened to 8x the serving peer's RTO when it measures slower —
        benching a healthy-but-distant peer for serving at the speed of
        light would thrash the peer rotation on every WAN batch."""
        if pub is None:
            return self.request_timeout
        rtt = getattr(self.network, "rtt", None)
        if rtt is None:
            return self.request_timeout
        return max(self.request_timeout, 8.0 * rtt.rto(pub))

    def _maybe_request(self) -> None:
        if self._request_inflight:
            now = asyncio.get_event_loop().time()
            timeout = self._request_timeout_for(self._request_peer)
            if now - self._request_time < timeout:
                return
            # request timed out: bench the unresponsive peer and rotate
            if self._request_peer is not None:
                self._bench_peer(self._request_peer)
            self._request_inflight = False
            self._request_peer = None
        best = self._best_peer()
        if best is None:
            return
        pub, their = best
        mine = self.bm.current_height()
        if their <= mine:
            return
        count = min(their - mine, MAX_BLOCKS_PER_REQUEST)
        self._request_inflight = True
        self._request_peer = pub
        self._request_start = mine + 1
        self._request_time = asyncio.get_event_loop().time()
        self.network.send_to(pub, wire.sync_blocks_request(mine + 1, count))

    # -- serving -----------------------------------------------------------

    def _on_blocks_request(self, sender: bytes, start: int, count: int) -> None:
        count = min(count, MAX_BLOCKS_PER_REQUEST)
        out: List[Tuple[Block, List[SignedTransaction]]] = []
        for height in range(start, start + count):
            block = self.bm.block_by_height(height)
            if block is None:
                break
            txs = []
            missing = False
            for h in block.tx_hashes:
                stx = self.bm.transaction_by_hash(h)
                if stx is None:
                    missing = True
                    break
                txs.append(stx)
            if missing:
                break
            out.append((block, txs))
        # always reply, even with no blocks — the requester uses the reply to
        # clear its inflight flag; silence would otherwise wedge its sync
        self.network.send_to(sender, wire.sync_blocks_reply(out))

    def _on_pool_request(self, sender: bytes, hashes: List[bytes]) -> None:
        txs = [stx for h in hashes if (stx := self.pool.get(h)) is not None]
        if txs:
            self.network.send_to(sender, wire.sync_pool_reply(txs))

    # -- applying ----------------------------------------------------------

    def _on_blocks_reply(
        self, sender: bytes, blocks: List[Tuple[Block, List[SignedTransaction]]]
    ) -> None:
        awaited = self._request_inflight and sender == self._request_peer
        mine_before = self.bm.current_height()
        applied = 0
        for block, txs in blocks:
            if self.handle_block(block, txs):
                applied += 1
            else:
                break
        if applied:
            self._new_block.set()
        if not awaited:
            # stale or unsolicited reply: blocks above were still applied if
            # valid, but it must not cancel a live request to another peer
            # (that would spawn duplicate concurrent requests)
            return
        req_start = self._request_start
        self._request_inflight = False
        self._request_peer = None
        if self.bm.current_height() > mine_before:
            pass  # real progress
        elif any(
            req_start <= blk.header.index <= mine_before for blk, _ in blocks
        ):
            # we raced ahead of the request (our own consensus committed the
            # blocks first); the peer honestly served what we asked for —
            # benching it would starve sync of its best peers at the tip
            pass
        elif self.peer_heights.get(sender, 0) > mine_before:
            # the peer advertises more blocks than us but served nothing
            # usable (empty reply, gap, bad multisig, stale spam): bench it
            # so the next request rotates instead of hot-looping against it
            self._bench_peer(sender)
        self._maybe_request()

    def handle_block(
        self, block: Block, txs: List[SignedTransaction]
    ) -> bool:
        """Verify + execute one synced block at the current tip
        (reference HandleBlockFromPeer, BlockSynchronizer.cs:110-180)."""
        mine = self.bm.current_height()
        if block.header.index <= mine:
            return True  # already have it
        if block.header.index != mine + 1:
            return False  # gap; re-request from tip
        prev = self.bm.block_by_height(mine)
        if prev is not None and block.header.prev_block_hash != prev.hash():
            logger.warning("synced block %d does not link", block.header.index)
            return False
        if not verify_block_multisig(
            block, self.keys_provider(block.header.index)
        ):
            logger.warning(
                "synced block %d lacks a signature quorum", block.header.index
            )
            return False
        if {t.hash() for t in txs} != set(block.tx_hashes):
            logger.warning("synced block %d tx set mismatch", block.header.index)
            return False
        try:
            self.bm.execute_block(
                block.header, txs, block.multisig, check_state_hash=True
            )
        except ValueError:
            logger.exception("synced block %d failed execution", block.header.index)
            return False
        self.pool.remove_included(block.tx_hashes)
        return True

    async def wait_for_height(self, height: int, timeout: float = 60.0) -> None:
        """Block until the local chain reaches `height`."""
        deadline = asyncio.get_running_loop().time() + timeout
        while self.bm.current_height() < height:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"sync stalled at {self.bm.current_height()} < {height}"
                )
            self._new_block.clear()
            try:
                await asyncio.wait_for(self._new_block.wait(), min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
