"""ValidatorStatusManager: the stake -> VRF -> submit loop.

Parity with the reference's background thread
(/root/reference/src/Lachain.Core/ValidatorStatus/ValidatorStatusManager.cs:
104, 219-266, 343-360, 432-440): once the node's address holds stake, each
cycle's VRF submission phase it evaluates the lottery (Vrf.Evaluate over
seed||cycle, stake-weighted winner check) and submits a SubmitVrf tx; it
also drives the two-phase stake-withdrawal flow. Event-driven here (hooked
on block persistence) instead of a polling thread.
"""
from __future__ import annotations

import logging
from typing import Callable, Optional

from ..crypto import ecdsa, vrf
from ..storage.state import Snapshot
from ..utils.serialization import Reader, write_bytes, write_u32, write_u64, write_u256
from . import system_contracts as sc
from .types import Block

logger = logging.getLogger(__name__)


class ValidatorStatusManager:
    def __init__(
        self,
        ecdsa_priv: bytes,
        send_tx: Callable[[bytes, bytes], None],
        *,
        cycle_duration: Optional[int] = None,
        vrf_phase: Optional[int] = None,
        attendance_reader: Optional[Callable[[int], dict]] = None,
    ):
        self._priv = ecdsa_priv
        self.public_key = ecdsa.public_key_bytes(ecdsa_priv)
        self.address = ecdsa.address_from_public_key(self.public_key)
        self._send_tx = send_tx
        self._cycle_duration = cycle_duration or sc.CYCLE_DURATION
        self._vrf_phase = vrf_phase or sc.VRF_SUBMISSION_PHASE
        # attendance_reader(cycle) -> {validator_pubkey: blocks_cosigned}
        # (the node's durable ValidatorAttendance counts)
        self._attendance_reader = attendance_reader
        self._submitted_cycles: set = set()
        self.withdraw_requested = False

    def _storage(self, snap: Snapshot, key: bytes) -> Optional[bytes]:
        return snap.get("storage", sc.STAKING_ADDRESS + key)

    def stake_of(self, snap: Snapshot) -> int:
        raw = self._storage(snap, b"stake:" + self.address)
        return int.from_bytes(raw, "big") if raw else 0

    # -- block hook ---------------------------------------------------------

    def on_block_persisted(self, block: Block, snap: Snapshot) -> None:
        height = block.header.index
        cycle = height // self._cycle_duration
        self._attendance_detection(height, cycle, snap)
        in_phase = height % self._cycle_duration < self._vrf_phase
        if not in_phase:
            # submission phase over: close the lottery if nobody has yet
            # (reference injects FinishVrfLottery as a system tx at the
            # phase boundary, BlockProducer.cs:126-146; here every validator
            # offers the closing tx and the contract dedupes)
            self._maybe_finish_lottery(cycle, snap)
            return
        if cycle in self._submitted_cycles:
            return
        stake = self.stake_of(snap)
        if stake == 0:
            return
        total_raw = self._storage(snap, b"total")
        total = int.from_bytes(total_raw, "big") if total_raw else 0
        if total == 0:
            return
        seed = self._storage(snap, b"seed") or b"genesis-seed"
        alpha = seed + write_u64(cycle)
        proof, beta = vrf.evaluate(self._priv, alpha)
        expected = int.from_bytes(
            self._storage(snap, b"validators_count") or write_u32(7), "big"
        )
        if not vrf.is_winner(beta, stake, total, expected):
            logger.debug("cycle %d: not a lottery winner", cycle)
            self._submitted_cycles.add(cycle)
            return
        logger.info("cycle %d: winning VRF roll, submitting", cycle)
        self._submitted_cycles.add(cycle)
        self._send_tx(
            sc.STAKING_ADDRESS,
            sc.SEL_SUBMIT_VRF
            + write_bytes(self.public_key)
            + write_bytes(proof),
        )

    def _attendance_detection(
        self, height: int, cycle: int, snap: Snapshot
    ) -> None:
        """Drive the attendance-detection phase (reference: the node's
        KeyGenManager/system-tx plumbing around
        StakingContract.SubmitAttendanceDetection, cs:538-634):
          * during the detection window of cycle >= 1, submit the previous
            cycle's locally-recorded co-signing counts for every electorate
            member — self-healing (re-offer until the on-chain check-in flag
            for our key appears);
          * once the window closes, offer the finish tx until the on-chain
            done flag appears (the contract dedupes)."""
        if cycle == 0 or self._attendance_reader is None:
            return
        in_window = (
            height % self._cycle_duration < sc.ATTENDANCE_DETECTION_DURATION
        )
        cyc = write_u64(cycle)
        if in_window:
            raw = self._storage(snap, b"att_checkin:" + cyc)
            if raw is not None and self.public_key in Reader(raw).bytes_list():
                return  # already checked in on-chain
            prev_raw = self._storage(snap, b"prev_pubs")
            prev_pubs = Reader(prev_raw).bytes_list() if prev_raw else []
            if self.public_key not in prev_pubs:
                return  # not in the electorate
            counts = self._attendance_reader(cycle - 1)
            entries = [
                write_bytes(
                    pub
                    + min(
                        counts.get(pub, 0), self._cycle_duration
                    ).to_bytes(4, "big")
                )
                for pub in prev_pubs
            ]
            logger.info("cycle %d: submitting attendance detection", cycle)
            self._send_tx(
                sc.STAKING_ADDRESS,
                sc.SEL_SUBMIT_ATTENDANCE
                + write_u32(len(entries))
                + b"".join(entries),
            )
        else:
            if self._storage(snap, b"att_done:" + cyc) is not None:
                return
            if self._storage(snap, b"prev_pubs") is None:
                return
            logger.info("cycle %d: closing attendance detection", cycle)
            self._send_tx(sc.STAKING_ADDRESS, sc.SEL_FINISH_ATTENDANCE + b"")

    def _maybe_finish_lottery(self, cycle: int, snap: Snapshot) -> None:
        # self-healing: re-offer every block until the on-chain
        # lottery_done flag appears — a lost or mistimed close tx must not
        # skip the cycle's rotation (no local one-shot latch; the chain
        # state IS the dedupe)
        winners = self._storage(snap, b"winners:" + write_u64(cycle))
        done = self._storage(snap, b"lottery_done:" + write_u64(cycle))
        if winners is None or done is not None:
            return
        logger.info("cycle %d: closing the VRF lottery", cycle)
        self._send_tx(sc.STAKING_ADDRESS, sc.SEL_FINISH_LOTTERY + b"")

    # -- stake lifecycle ----------------------------------------------------

    def become_staker(self, amount: int) -> None:
        self._send_tx(
            sc.STAKING_ADDRESS,
            sc.SEL_BECOME_STAKER + write_bytes(self.public_key) + write_u256(amount),
        )

    def request_withdrawal(self) -> None:
        self.withdraw_requested = True
        self._send_tx(sc.STAKING_ADDRESS, sc.SEL_REQUEST_WITHDRAW + b"")

    def withdraw(self) -> None:
        self._send_tx(sc.STAKING_ADDRESS, sc.SEL_WITHDRAW + b"")
