"""In-process TCP fleet harness: WAN shaping + zero-downtime rolling upgrades.

Boots N full Nodes (core/node.py) over real loopback TCP — signed batches,
per-peer workers, synchronizer, watchdog — the same stack a container fleet
runs, minus the containers. Three jobs:

  * **WAN emulation**: a `LinkShaper` (network/faults.py) installed on every
    node's TcpFrameFilter stripes the fleet across emulated regions with a
    per-region-pair latency/jitter/bandwidth matrix, seeded so two same-seed
    runs shape identically.
  * **Rolling upgrades**: `roll_node(i)` stops node i, rebuilds it from the
    same keys on the upgraded wire (`network/wire.py` LTRX handshake), and
    waits for it to resync and read healthy before the next roll — the
    `lachain-tpu fleet-upgrade` drill and the upgrade tests drive this.
  * **Deterministic traffic**: `submit_and_settle()` paces open-loop load so
    every live node's pool agrees before an era proposes. With
    txs_per_block >= the paced batch size, every proposer proposes the same
    set, the HB union is that set regardless of which proposer slots decide,
    and committed block content is identical between a drill run and its
    no-upgrade control — the block-hash gate the upgrade test asserts.

The harness is test/CLI infrastructure, not a production entrypoint; real
fleets are composed from configs (DEPLOY.md "WAN operations & rolling
upgrades").
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Dict, List, Optional

from ..consensus.keys import trusted_key_gen
from ..network.faults import FaultPlan, LinkShaper
from .node import Node
from .types import SignedTransaction

logger = logging.getLogger(__name__)

DEFAULT_CHAIN_ID = 225


class TcpFleet:
    """N validators over loopback TCP, optionally link-shaped, rollable."""

    def __init__(
        self,
        n: int = 6,
        f: int = 1,
        *,
        chain_id: int = DEFAULT_CHAIN_ID,
        seed: int = 0,
        txs_per_block: int = 128,
        initial_balances: Optional[Dict[bytes, int]] = None,
        flush_interval: float = 0.01,
        shaper: Optional[LinkShaper] = None,
        fault_seed: int = 0,
        legacy_wire: bool = False,
        era_timeout: float = 60.0,
    ):
        self.n, self.f = n, f
        self.chain_id = chain_id
        self.txs_per_block = txs_per_block
        self.flush_interval = flush_interval
        self.shaper = shaper
        self.fault_seed = fault_seed
        # legacy_wire=True boots every node WITHOUT the LTRX version
        # handshake (a pre-handshake build): the rolling-upgrade drill
        # starts here and rolls node-by-node onto the advertising wire,
        # making the roll a genuine mixed-version upgrade
        self.legacy_wire = legacy_wire
        self.era_timeout = era_timeout
        self.initial_balances = dict(initial_balances or {})
        rng = random.Random(seed)

        class _Rng:
            def randbelow(self, k):
                return rng.randrange(k)

        self.public_keys, self.private_keys = trusted_key_gen(n, f, rng=_Rng())
        self.nodes: List[Optional[Node]] = [None] * n
        self.upgraded: List[bool] = [False] * n
        # eras each node missed while down (the zero-missed-eras gate is
        # about the FLEET: every era must commit; a rolling node sitting
        # one out is the expected shape, a fleet-wide miss is the failure)
        self.missed_eras: Dict[int, List[int]] = {}

    # -- boot ---------------------------------------------------------------

    def _make_node(self, i: int, *, upgraded: bool) -> Node:
        node = Node(
            index=i,
            public_keys=self.public_keys,
            private_keys=self.private_keys[i],
            chain_id=self.chain_id,
            initial_balances=self.initial_balances,
            txs_per_block=self.txs_per_block,
            flush_interval=self.flush_interval,
        )
        if self.legacy_wire and not upgraded:
            # pre-handshake build: no LTRX advert on outbound batches
            node.network.factory.handshake = False
        return node

    def _install_shaper(self, node: Node, i: int) -> None:
        if self.shaper is None:
            return
        node.network.install_faults(
            FaultPlan(seed=self.fault_seed, shaper=self.shaper), i
        )
        for j, pub in enumerate(self.public_keys.ecdsa_pub_keys):
            node.network.map_fault_peer(pub, j)

    async def start(self, first_era: int = 1) -> None:
        for i in range(self.n):
            node = self._make_node(i, upgraded=False)
            self.nodes[i] = node
            await node.start(first_era)
            self._install_shaper(node, i)
        self._connect_all()

    def _connect_all(self) -> None:
        addrs = [nd.address for nd in self.nodes if nd is not None]
        for nd in self.nodes:
            if nd is not None:
                nd.connect([a for a in addrs if a.public_key != nd.ecdsa_pub])

    def live(self) -> List[Node]:
        return [nd for nd in self.nodes if nd is not None]

    def region_of(self, i: int) -> str:
        return self.shaper.region_of(i) if self.shaper is not None else ""

    # -- paced open-loop traffic -------------------------------------------

    async def submit_and_settle(
        self, txs: List[SignedTransaction], *, timeout: float = 30.0
    ) -> None:
        """Submit `txs` to the first live node and wait until every live
        node's pool holds all of them — the pacing that makes proposals
        (hence committed block content) identical across runs."""
        entry = self.live()[0]
        for stx in txs:
            if not entry.submit_tx(stx):
                raise RuntimeError(f"tx rejected by pool: {stx.hash().hex()}")
        hashes = [stx.hash() for stx in txs]
        deadline = time.monotonic() + timeout
        while True:
            settled = all(
                all(nd.pool.get(h) is not None for h in hashes)
                for nd in self.live()
            )
            if settled:
                return
            if time.monotonic() > deadline:
                raise TimeoutError("tx gossip did not settle fleet-wide")
            await asyncio.sleep(0.02)

    # -- era loop -----------------------------------------------------------

    async def run_era(self, era: int) -> bytes:
        """Run era `era` on every live node; records the miss for any node
        sitting it out (mid-roll). Returns the committed header hash —
        identical on every live node or this raises."""
        live = self.live()
        for i, nd in enumerate(self.nodes):
            if nd is None:
                self.missed_eras.setdefault(i, []).append(era)
        blocks = await asyncio.gather(
            *(nd.run_era(era, timeout=self.era_timeout) for nd in live)
        )
        hashes = {b.header.hash() for b in blocks}
        if len(hashes) != 1:
            raise RuntimeError(f"era {era}: fleet forked ({len(hashes)} heads)")
        return hashes.pop()

    def health_statuses(self) -> Dict[int, str]:
        return {
            i: nd.health()["status"]
            for i, nd in enumerate(self.nodes)
            if nd is not None
        }

    # -- rolling upgrade ----------------------------------------------------

    async def take_down(self, i: int) -> int:
        """Stop node i for its upgrade window; returns its tip height.
        Survivors keep running eras (the caller drives them) — n-f must
        still clear quorum with one node out, which is exactly the
        zero-downtime claim the drill certifies."""
        old = self.nodes[i]
        assert old is not None
        tip = old.block_manager.current_height()
        self.nodes[i] = None
        await old.stop()
        return tip

    async def bring_up(
        self, i: int, *, next_era: int, resync_timeout: float = 60.0
    ) -> Node:
        """Rebuild node i on the upgraded wire (LTRX handshake on),
        reconnect it, and wait until it has resynced to the CURRENT fleet
        tip — including any eras the survivors committed while it was
        down. Fresh store on purpose (the harsher restart): the node must
        resync every block over the upgraded wire, exercising sync interop
        between wire versions, not just consensus interop."""
        assert self.nodes[i] is None, "take_down first"
        node = self._make_node(i, upgraded=True)
        self.upgraded[i] = True
        await node.start(next_era)
        self._install_shaper(node, i)
        self.nodes[i] = node
        self._connect_all()
        target = max(
            nd.block_manager.current_height()
            for nd in self.live()
            if nd is not node
        )
        deadline = time.monotonic() + resync_timeout
        while node.block_manager.current_height() < target:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"node {i} did not resync to height {target} after "
                    "upgrade"
                )
            await asyncio.sleep(0.05)
        return node

    async def stop(self) -> None:
        for nd in self.live():
            await nd.stop()

    # -- observability ------------------------------------------------------

    def rtt_ms(self) -> float:
        """Max observed SRTT across the fleet, in ms (the curve's x axis)."""
        vals = [nd.network.rtt.max_srtt() for nd in self.live()]
        return round(max(vals) * 1000.0, 3) if vals else 0.0

    def wire_versions(self) -> Dict[int, int]:
        return {
            i: nd.network.factory.wire_version
            if nd.network.factory.handshake
            else 1
            for i, nd in enumerate(self.nodes)
            if nd is not None
        }
