"""Transaction pool (mempool).

Parity with the reference's TransactionPool
(/root/reference/src/Lachain.Core/Blockchain/Pool/TransactionPool.cs):
  * Add: signature verify + nonce bookkeeping + persistence (130-148)
  * Peek: fee-ordered proposal sampling with per-sender nonce continuity
    (401+; NonceCalculator.cs:21)
  * Restore from the persistent repo on startup (98+)
  * eviction of included/stale transactions

Admission is SHARDED: the pool's maps are split across `_N_SHARDS`
independent lock domains keyed by the sender address, so concurrent
`add()` calls from the RPC/gossip ingest threads only serialize when two
transactions share a sender shard. The expensive step — ECDSA sender
recovery — runs OUTSIDE every lock. `txpool_admit_lock_wait_seconds`
histograms the time an admitting thread spends blocked on its shard lock,
which is the direct measure of residual admission contention.

Lock ordering: shard lock -> `_nonce_lock` (state-trie nonce reads; the
trie's LRU cache is not thread-safe). No path acquires two shard locks
at once, so there is no cross-shard ordering to get wrong.
"""
from __future__ import annotations

import heapq
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..storage.kv import EntryPrefix, KVStore, prefixed
from ..utils import metrics, txtrace
from .types import SignedTransaction

_N_SHARDS = 16

# shard-lock waits are sub-microsecond uncontended; buckets resolve the
# interesting range (lock convoy under ingest bursts)
_LOCK_WAIT_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1)


class _PoolShard:
    """One lock domain: the slice of the pool whose senders hash here."""

    __slots__ = ("lock", "txs", "senders", "by_nonce")

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.txs: Dict[bytes, SignedTransaction] = {}
        self.senders: Dict[bytes, bytes] = {}  # tx hash -> sender
        # (sender, nonce) -> tx hash (reference TransactionHashTrackerByNonce)
        self.by_nonce: Dict[Tuple[bytes, int], bytes] = {}


class TransactionPool:
    def __init__(
        self,
        kv: KVStore,
        chain_id: int,
        account_nonce: Callable[[bytes], int],
        min_gas_price: int = 1,
    ):
        self._kv = kv
        self.chain_id = chain_id
        self._account_nonce_fn = account_nonce
        self.min_gas_price = min_gas_price
        self._shards = [_PoolShard() for _ in range(_N_SHARDS)]
        # state-trie nonce reads go through the trie's LRU cache, which is
        # not safe under concurrent mutation — serialize them
        self._nonce_lock = threading.Lock()

    def _shard(self, sender: bytes) -> _PoolShard:
        return self._shards[sender[0] % _N_SHARDS]

    def _account_nonce(self, sender: bytes) -> int:
        with self._nonce_lock:
            return self._account_nonce_fn(sender)

    def __len__(self) -> int:
        return sum(len(s.txs) for s in self._shards)

    # -- ingress --------------------------------------------------------------
    def precheck(self, stx: SignedTransaction) -> bool:
        """The cheap admission checks only (dedup + gas floor) — no
        signature recovery. Bulk-ingest callers filter through this BEFORE
        paying for batch sender recovery, so re-gossiped duplicates cost a
        hash lookup, not an ECDSA recover. Advisory by design (add()
        re-checks under the shard lock), so the dict probes run lock-free."""
        if stx.tx.gas_price < self.min_gas_price:
            return False
        h = stx.hash()
        return all(h not in s.txs for s in self._shards)

    def add(self, stx: SignedTransaction) -> bool:
        """Verify + admit. Returns False (and drops) on any rule violation."""
        h = stx.hash()
        if stx.tx.gas_price < self.min_gas_price:
            return False
        if any(h in s.txs for s in self._shards):
            return False  # lock-free dedup; re-checked under the shard lock
        # ECDSA recovery is the expensive step — outside every lock
        sender = stx.sender(self.chain_id)
        if sender is None:
            return False
        shard = self._shard(sender)
        t0 = time.perf_counter()
        with shard.lock:
            metrics.observe_hist(
                "txpool_admit_lock_wait_seconds",
                time.perf_counter() - t0,
                buckets=_LOCK_WAIT_BUCKETS,
            )
            if h in shard.txs:
                return False
            current = self._account_nonce(sender)
            if stx.tx.nonce < current:
                return False  # already used
            key = (sender, stx.tx.nonce)
            if key in shard.by_nonce:
                # replacement only for strictly higher fee
                old = shard.txs.get(shard.by_nonce[key])
                if old is not None and stx.tx.gas_price <= old.tx.gas_price:
                    return False
                self._evict_in_shard(shard, shard.by_nonce[key])
            shard.txs[h] = stx
            shard.senders[h] = sender
            shard.by_nonce[key] = h
            # the pool's crash window: admitted to memory, not yet in the
            # crash-restore repository — a kill here loses the tx from the
            # restart (best-effort by design; gossip re-fills)
            from ..storage.crashpoints import crash_point

            crash_point("pool.save.mid")
            self._kv.put(prefixed(EntryPrefix.POOL_TX, h), stx.encode())
        # tx lifecycle stamp OUTSIDE the shard lock (admission succeeded;
        # sampled-only, first stamp wins across gossip re-admissions)
        txtrace.stamp(h, "pool")
        return True

    # -- proposal --------------------------------------------------------------
    def next_nonce(self, sender: bytes) -> int:
        """Next usable nonce for `sender`: the account nonce advanced past
        any consecutive pending transactions already in the pool."""
        shard = self._shard(sender)
        with shard.lock:
            nonce = self._account_nonce(sender)
            while (sender, nonce) in shard.by_nonce:
                nonce += 1
            return nonce

    def peek(
        self,
        max_txs: int,
        rng: Optional["random.Random"] = None,
        window_txs: Optional[int] = None,
        exclude: Optional[Set[bytes]] = None,
        nonce_override: Optional[Dict[bytes, int]] = None,
    ) -> List[SignedTransaction]:
        """Fee-ordered proposal with per-sender nonce continuity.

        With `rng`, the proposal is a RANDOM sample from a fee-ordered
        window of up to `window_txs` executable txs (the reference's
        RandomSamplingQueue role, Containers/RandomSamplingQueue.cs):
        HoneyBadger blocks carry the UNION of n proposals, so diversity
        across validators — not identical top-fee picks — is what fills
        blocks. The window must therefore span a whole BLOCK's worth of
        txs, not one proposal's worth: n validators sampling 4*max_txs
        txs can union to at most 4*max_txs distinct entries. Sampling
        keeps per-sender nonce chains contiguous by sampling SENDERS,
        then taking their chain prefixes.

        `exclude` / `nonce_override` are the pipelined-proposal overlay:
        when proposing on top of in-flight (decided but uncommitted) blocks,
        the caller masks txs already claimed by those blocks and advances
        the per-sender chain start past their nonces — state reads still
        see the committed trie, which is exactly the sequential outcome
        once the in-flight blocks land."""
        if rng is not None:
            window = self._peek_ordered_with_senders(
                window_txs if window_txs is not None else 4 * max_txs,
                exclude=exclude,
                nonce_override=nonce_override,
            )
            if len(window) > max_txs:
                by_sender: Dict[bytes, List[SignedTransaction]] = {}
                order: List[bytes] = []
                for s, stx in window:
                    if s not in by_sender:
                        by_sender[s] = []
                        order.append(s)
                    by_sender[s].append(stx)
                rng.shuffle(order)
                picked: List[SignedTransaction] = []
                for s in order:
                    take = min(len(by_sender[s]), max_txs - len(picked))
                    picked.extend(by_sender[s][:take])
                    if len(picked) >= max_txs:
                        break
                return picked
            return [stx for _, stx in window]
        return self._peek_ordered(
            max_txs, exclude=exclude, nonce_override=nonce_override
        )

    def _snapshot(self) -> List[Tuple[bytes, bytes, SignedTransaction]]:
        """(hash, sender, tx) triples — each shard copied under its own
        lock, the union processed lock-free by the caller."""
        out: List[Tuple[bytes, bytes, SignedTransaction]] = []
        for shard in self._shards:
            with shard.lock:
                out.extend(
                    (h, shard.senders[h], stx) for h, stx in shard.txs.items()
                )
        return out

    def _peek_ordered(
        self,
        max_txs: int,
        exclude: Optional[Set[bytes]] = None,
        nonce_override: Optional[Dict[bytes, int]] = None,
    ) -> List[SignedTransaction]:
        return [
            stx
            for _, stx in self._peek_ordered_with_senders(
                max_txs, exclude=exclude, nonce_override=nonce_override
            )
        ]

    def _peek_ordered_with_senders(
        self,
        max_txs: int,
        exclude: Optional[Set[bytes]] = None,
        nonce_override: Optional[Dict[bytes, int]] = None,
    ) -> List[Tuple[bytes, SignedTransaction]]:
        per_sender: Dict[bytes, List[SignedTransaction]] = {}
        for h, sender, stx in self._snapshot():
            if exclude is not None and h in exclude:
                continue  # claimed by an in-flight block
            per_sender.setdefault(sender, []).append(stx)
        # per-sender executable chains, nonce-ascending
        chains: Dict[bytes, List[SignedTransaction]] = {}
        for sender, txs in per_sender.items():
            txs.sort(key=lambda t: t.tx.nonce)
            if nonce_override is not None and sender in nonce_override:
                nonce = nonce_override[sender]
            else:
                nonce = self._account_nonce(sender)
            chain = []
            for t in txs:
                if t.tx.nonce != nonce:
                    break  # gap: later nonces are unexecutable
                chain.append(t)
                nonce += 1
            if chain:
                chains[sender] = chain
        # repeatedly take the highest-fee among the next-executable txs,
        # so a cheap prerequisite nonce never strands an expensive later
        # one (chain heads advance as they are picked). Heap keys are
        # precomputed — one hash per tx, not per comparison.
        def heap_key(stx: SignedTransaction):
            h = stx.hash()
            return (-stx.tx.gas_price, bytes(255 - b for b in h))

        picked: List[Tuple[bytes, SignedTransaction]] = []
        heap = [(heap_key(chain[0]), s, 0) for s, chain in chains.items()]
        heapq.heapify(heap)
        while len(picked) < max_txs and heap:
            _, s, i = heapq.heappop(heap)
            picked.append((s, chains[s][i]))
            if i + 1 < len(chains[s]):
                heapq.heappush(heap, (heap_key(chains[s][i + 1]), s, i + 1))
        return picked

    # -- lifecycle --------------------------------------------------------------
    def remove_included(self, tx_hashes) -> None:
        for h in tx_hashes:
            self._evict(h)

    def sanitize(self) -> int:
        """Drop txs whose nonce is now stale (reference sanitize-on-persist,
        TransactionPool.cs:79-90). Returns number evicted."""
        n = 0
        for shard in self._shards:
            with shard.lock:
                stale = [
                    h
                    for h, stx in shard.txs.items()
                    if stx.tx.nonce < self._account_nonce(shard.senders[h])
                ]
                for h in stale:
                    self._evict_in_shard(shard, h)
                n += len(stale)
        return n

    def restore(self) -> int:
        """Reload persisted pool txs (reference Restore, TransactionPool.cs:98)."""
        count = 0
        for key, enc in self._kv.scan_prefix(prefixed(EntryPrefix.POOL_TX)):
            try:
                stx = SignedTransaction.decode(enc)
            except (ValueError, AssertionError):
                self._kv.delete(key)
                continue
            if self.add(stx):
                count += 1
            else:
                # rejected on re-admission (stale nonce, fee floor, ...):
                # drop the persisted entry or it is re-read every restart
                self._kv.delete(key)
        return count

    def _evict(self, h: bytes) -> None:
        # hash alone does not name the shard — probe each, one lock at a
        # time (never nested, so shard locks stay unordered)
        for shard in self._shards:
            with shard.lock:
                if h in shard.txs:
                    self._evict_in_shard(shard, h)
                    return
        self._kv.delete(prefixed(EntryPrefix.POOL_TX, h))

    def _evict_in_shard(self, shard: _PoolShard, h: bytes) -> None:
        """Caller holds shard.lock."""
        stx = shard.txs.pop(h, None)
        sender = shard.senders.pop(h, None)
        if stx is not None and sender is not None:
            shard.by_nonce.pop((sender, stx.tx.nonce), None)
        self._kv.delete(prefixed(EntryPrefix.POOL_TX, h))

    def tx_hashes(self) -> set:
        """Snapshot of pooled tx hashes (pending-tx filters)."""
        out = set()
        for shard in self._shards:
            with shard.lock:
                out.update(shard.txs)
        return out

    def clear(self) -> None:
        """Drop every pooled tx, memory AND persisted entries (reference
        clearInMemoryPool + repository delete, TransactionPool.cs)."""
        for shard in self._shards:
            with shard.lock:
                for h in list(shard.txs):
                    self._evict_in_shard(shard, h)

    def persisted_hashes(self) -> List[bytes]:
        """Hashes of txs currently saved in the crash-restore repository."""
        plen = len(prefixed(EntryPrefix.POOL_TX))
        return [
            key[plen:]
            for key, _ in self._kv.scan_prefix(prefixed(EntryPrefix.POOL_TX))
        ]

    def clear_persisted(self) -> int:
        """Wipe the crash-restore repository WITHOUT touching the live pool
        (reference deleteTransactionPoolRepository)."""
        n = 0
        for key, _ in list(
            self._kv.scan_prefix(prefixed(EntryPrefix.POOL_TX))
        ):
            self._kv.delete(key)
            n += 1
        return n

    def get(self, h: bytes) -> Optional[SignedTransaction]:
        for shard in self._shards:
            stx = shard.txs.get(h)
            if stx is not None:
                return stx
        return None
