"""KeyGenManager: drives the on-chain DKG from system-contract events.

Parity with the reference's manager
(/root/reference/src/Lachain.Core/Vault/KeyGenManager.cs:77-260): watch
executed blocks for staking/governance events and answer with the next
keygen transaction —

  lottery_done       -> if elected, new TrustlessKeygen + COMMIT tx
  keygen_commit      -> handle_commit  -> SEND_VALUE tx
  keygen_value       -> handle_send_value; once finished -> CONFIRM tx
                        carrying the derived public key set
  validators_changed -> install the keyring shares into the wallet for the
                        next cycle's eras (PrivateWallet era-keyed store)

The manager is transport-agnostic: `send_tx(to, invocation)` is provided by
the node (it builds, signs, pools, and gossips the transaction).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

from ..consensus.keygen import CommitMessage, ThresholdKeyring, TrustlessKeygen, ValueMessage
from ..crypto import ecdsa
from ..storage.kv import EntryPrefix, prefixed
from ..storage.state import Snapshot
from ..utils.serialization import (
    Reader,
    write_bytes,
    write_bytes_list,
    write_u32,
    write_u64,
    write_u256,
)
from . import system_contracts as sc
from .types import Block

logger = logging.getLogger(__name__)


class KeyGenManager:
    def __init__(
        self,
        ecdsa_priv: bytes,
        send_tx: Callable[[bytes, bytes], None],
        *,
        cycle_duration: Optional[int] = None,
        on_keys: Optional[Callable[[int, ThresholdKeyring, List[bytes]], None]] = None,
        rng=None,
        kv=None,
    ):
        self._priv = ecdsa_priv
        self.public_key = ecdsa.public_key_bytes(ecdsa_priv)
        self.address = ecdsa.address_from_public_key(self.public_key)
        self._send_tx = send_tx
        self._cycle_duration = cycle_duration or sc.CYCLE_DURATION
        self._on_keys = on_keys  # (first_era, keyring, participant_pubkeys)
        self._rng = rng
        self.keygen: Optional[TrustlessKeygen] = None
        self._participants: List[bytes] = []
        self._addr_to_idx: Dict[bytes, int] = {}
        self._keyring: Optional[ThresholdKeyring] = None
        self._cycle: Optional[int] = None
        self._installed_cycles: set = set()
        # crash durability: the full DKG state persists after EVERY step so
        # a validator restarting mid-keygen rejoins the cycle instead of
        # losing its slot (reference persists via KeyGenRepository after
        # each handler, ThresholdKeygen/TrustlessKeygen.cs:195-261 +
        # ConsensusManager.cs:250-266 rescan)
        self._kv = kv
        if kv is not None:
            self._load_state()

    _STATE_KEY = prefixed(EntryPrefix.KEYGEN_STATE)

    def _persist_state(self) -> None:
        if self._kv is None:
            return
        out = write_u64(
            self._cycle if self._cycle is not None else (1 << 64) - 1
        )
        out += write_bytes_list(list(self._participants))
        out += write_bytes(self.keygen.to_bytes() if self.keygen else b"")
        out += write_u32(len(self._installed_cycles))
        for c in sorted(self._installed_cycles):
            out += write_u64(c)
        self._kv.put(self._STATE_KEY, out)

    def _load_state(self) -> None:
        raw = self._kv.get(self._STATE_KEY)
        if raw is None:
            return
        try:
            r = Reader(raw)
            cycle = r.u64()
            self._cycle = None if cycle == (1 << 64) - 1 else cycle
            self._participants = r.bytes_list()
            blob = r.bytes_()
            self._installed_cycles = {r.u64() for _ in range(r.u32())}
            r.assert_eof()
            self._addr_to_idx = {
                ecdsa.address_from_public_key(pk): i
                for i, pk in enumerate(self._participants)
            }
            if blob:
                self.keygen = TrustlessKeygen.from_bytes(blob, self._priv)
                self._keyring = self.keygen.try_get_keys()
            logger.info(
                "keygen state restored (cycle %s, in progress: %s)",
                self._cycle,
                self.keygen is not None,
            )
        except Exception:
            logger.exception("corrupt keygen state ignored")
            # reset EVERY restored field to pristine values — partially
            # restored cycle/participant/installed-cycle garbage could
            # silently skip the next key installation
            self.keygen = None
            self._keyring = None
            self._cycle = None
            self._participants = []
            self._addr_to_idx = {}
            self._installed_cycles = set()

    # -- block hook ---------------------------------------------------------

    def on_block_persisted(self, block: Block, snap: Snapshot) -> None:
        """Scan the block's executed events and react (reference
        BlockManagerOnSystemContractInvoked, KeyGenManager.cs:77-107)."""
        for tx_hash in block.tx_hashes:
            i = 0
            while True:
                raw = snap.get("events", tx_hash + write_u32(i))
                if raw is None:
                    break
                i += 1
                try:
                    self._handle_event(raw[:20], raw[20:], block, snap)
                except Exception:
                    logger.exception("keygen event handling failed")
        self._maybe_finish_cycle(block, snap)

    def _maybe_finish_cycle(self, block: Block, snap: Snapshot) -> None:
        """Once a confirmed rotation is pending, offer the FinishCycle tx
        after block D-2 persists so it executes in block D-1 — the only
        height the contract accepts (reference injects this as a
        cycle-boundary system tx, BlockProducer.cs:126-146). Exactly one
        block index per cycle satisfies the trigger, so chain state — not a
        local latch — is the dedupe; a restart or a missed boundary
        self-heals at the next cycle's window."""
        if (block.header.index + 2) % self._cycle_duration != 0:
            return
        pending = self._storage(
            snap, sc.GOVERNANCE_ADDRESS, b"pending_validators"
        )
        if pending is None:
            return
        self._send_tx(sc.GOVERNANCE_ADDRESS, sc.SEL_FINISH_CYCLE + b"")

    def _handle_event(
        self, contract: bytes, payload: bytes, block: Block, snap: Snapshot
    ) -> None:
        if contract == sc.STAKING_ADDRESS and payload.startswith(b"lottery_done"):
            self._on_lottery_done(block, snap)
        elif contract == sc.GOVERNANCE_ADDRESS and payload.startswith(b"keygen_commit"):
            rest = payload[len(b"keygen_commit") :]
            self._on_commit(rest[:20], rest[20:])
        elif contract == sc.GOVERNANCE_ADDRESS and payload.startswith(b"keygen_value"):
            rest = payload[len(b"keygen_value") :]
            self._on_value(rest[:20], rest[20:])
        elif contract == sc.GOVERNANCE_ADDRESS and payload.startswith(
            b"validators_changed"
        ):
            self._on_validators_changed(block, snap)

    # -- steps --------------------------------------------------------------

    def _storage(self, snap: Snapshot, contract: bytes, key: bytes):
        return snap.get("storage", contract + key)

    def _on_lottery_done(self, block: Block, snap: Snapshot) -> None:
        raw = self._storage(snap, sc.STAKING_ADDRESS, b"next_validators")
        if raw is None:
            return
        participants = Reader(raw).bytes_list()
        if self.public_key not in participants:
            self.keygen = None
            self._persist_state()
            return
        cycle = block.header.index // self._cycle_duration
        if self._cycle == cycle and self.keygen is not None:
            return  # already running
        self._cycle = cycle
        self._participants = participants
        self._addr_to_idx = {
            ecdsa.address_from_public_key(pk): i
            for i, pk in enumerate(participants)
        }
        n = len(participants)
        f = (n - 1) // 3
        kwargs = {"rng": self._rng} if self._rng is not None else {}
        self.keygen = TrustlessKeygen(
            self._priv, participants, f, cycle, **kwargs
        )
        self._keyring = None
        commit = self.keygen.start_keygen()
        self._persist_state()
        logger.info("elected for cycle %d: sending keygen commit", cycle)
        self._send_tx(
            sc.GOVERNANCE_ADDRESS,
            sc.SEL_KEYGEN_COMMIT + write_bytes(commit.to_bytes()),
        )

    def _on_commit(self, sender_addr: bytes, blob: bytes) -> None:
        if self.keygen is None:
            return
        dealer = self._addr_to_idx.get(sender_addr)
        if dealer is None:
            return
        try:
            vmsg = self.keygen.handle_commit(dealer, CommitMessage.from_bytes(blob))
        except ValueError:
            logger.warning("faulty commit from dealer %d ignored", dealer)
            return
        self._persist_state()
        self._send_tx(
            sc.GOVERNANCE_ADDRESS,
            sc.SEL_KEYGEN_SEND_VALUE
            + write_u256(dealer)
            + write_bytes(vmsg.to_bytes()),
        )

    def _on_value(self, sender_addr: bytes, blob: bytes) -> None:
        if self.keygen is None:
            return
        sender = self._addr_to_idx.get(sender_addr)
        if sender is None:
            return
        try:
            should_confirm = self.keygen.handle_send_value(
                sender, ValueMessage.from_bytes(blob)
            )
        except ValueError:
            logger.warning("faulty value from sender %d ignored", sender)
            return
        self._persist_state()
        if not should_confirm:
            return
        keyring = self.keygen.try_get_keys()
        if keyring is None:
            return
        self._keyring = keyring
        pub = keyring.public_keys(self.keygen.f, self._participants)
        self._send_tx(
            sc.GOVERNANCE_ADDRESS,
            sc.SEL_KEYGEN_CONFIRM + write_bytes(pub.encode()),
        )

    def _on_validators_changed(self, block: Block, snap: Snapshot) -> None:
        if self._keyring is None or self._cycle is None:
            return
        if self._cycle in self._installed_cycles:
            return
        self._installed_cycles.add(self._cycle)
        self._persist_state()
        first_era = (self._cycle + 1) * self._cycle_duration
        logger.info("keygen finished: keys installed from era %d", first_era)
        if self._on_keys is not None:
            self._on_keys(first_era, self._keyring, list(self._participants))
