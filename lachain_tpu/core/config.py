"""Node configuration: versioned JSON with sequential schema migrations.

Parity with the reference's ConfigManager
(/root/reference/src/Lachain.Core/Config/ConfigManager.cs:15-78): a config
file carries a `version` field; loading runs every migration from the file's
version up to CURRENT_VERSION in order, so operators can carry configs
across releases. Typed section accessors replace the reference's section
classes (NetworkConfig, GenesisConfig, VaultConfig, HardforkConfig...).
"""
from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Union

CURRENT_VERSION = 7

# "not scheduled yet" sentinel for migrated hardfork heights: far above any
# realistic chain height, so is_active() stays False until the operator
# coordinates a real activation height across the validator set
HARDFORK_HEIGHT_NEVER = 2**62

# -- migrations --------------------------------------------------------------
# each migrates version N -> N+1 (reference runs 17 of these sequentially)

_MIGRATIONS: Dict[int, Callable[[dict], dict]] = {}


def _migration(frm: int):
    def deco(fn):
        _MIGRATIONS[frm] = fn
        return fn

    return deco


@_migration(1)
def _v1_to_v2(cfg: dict) -> dict:
    # v2 split the flat "port" into a network section
    net = cfg.setdefault("network", {})
    if "port" in cfg:
        net.setdefault("port", cfg.pop("port"))
    net.setdefault("host", "127.0.0.1")
    return cfg


@_migration(2)
def _v2_to_v3(cfg: dict) -> dict:
    # v3 added staking cycle parameters and the hardfork section
    staking = cfg.setdefault("staking", {})
    staking.setdefault("cycleDuration", 1000)
    staking.setdefault("vrfSubmissionPhase", 500)
    cfg.setdefault("hardfork", {})
    return cfg


@_migration(3)
def _v3_to_v4(cfg: dict) -> dict:
    # v4 (round 4, gossip peer discovery): an explicit dialable address for
    # wildcard binds / NAT — None keeps the bind host
    cfg.setdefault("network", {}).setdefault("advertiseHost", None)
    return cfg


@_migration(4)
def _v4_to_v5(cfg: dict) -> dict:
    # v5 (round 4, on-chain attendance detection): the detection-window
    # length joined the consensus-critical cycle parameters. The default
    # scales with the config's OWN cycle (same formula keygen uses) so a
    # short-cycle chain never gets a window that outlives the cycle
    staking = cfg.setdefault("staking", {})
    cycle = int(staking.get("cycleDuration", 1000))
    staking.setdefault(
        "attendanceDetectionDuration", max(min(100, cycle // 5), 1)
    )
    return cfg


@_migration(5)
def _v5_to_v6(cfg: dict) -> dict:
    # v6 (round 4, fast_wasm_gas hardfork): configs carry the repricing
    # height explicitly. A MIGRATED config belongs to a chain that ran
    # under the old gas schedule, so defaulting to 0 would retroactively
    # reprice historical blocks and break resync-from-genesis validation.
    # Default to the far-future sentinel: the old schedule stays in force
    # until the operator coordinates an explicit upgrade height. Configs
    # generated fresh at v6 (cli.py keygen) write fast_wasm_gas: 0
    # explicitly, so they never hit this default.
    hf = cfg.setdefault("hardfork", {})
    hf.setdefault("heights", {}).setdefault(
        "fast_wasm_gas", HARDFORK_HEIGHT_NEVER
    )
    return cfg


@_migration(6)
def _v6_to_v7(cfg: dict) -> dict:
    # v7 (round 6): the default storage engine flipped to the native LSM.
    # A MIGRATED config belongs to a chain whose database was written by
    # sqlite; the two on-disk formats are not interchangeable, so flipping
    # it silently would abandon the existing chain and resync a fresh LSM
    # store from genesis. Pin what the config was actually running. Fresh
    # v7 configs (cli.py keygen) write engine: "lsm" explicitly.
    cfg.setdefault("storage", {}).setdefault("engine", "sqlite")
    return cfg


def migrate(cfg: dict) -> dict:
    cfg = copy.deepcopy(cfg)
    version = int(cfg.get("version", 1))
    if version > CURRENT_VERSION:
        raise ValueError(
            f"config version {version} is newer than supported "
            f"{CURRENT_VERSION}"
        )
    if version == 5:
        # a config SAVED at v5 belongs to a chain that ran round-4
        # software, whose builds activated fast_wasm_gas from genesis.
        # The v5->v6 migration default (the NEVER sentinel, correct for
        # pre-round-4 configs) would silently DEACTIVATE the repricing on
        # such a chain and fork it from peers on the next resync. There
        # is no safe guess, so refuse until the operator states the
        # height explicitly (DEPLOY.md "Upgrading v5 configs").
        heights = (cfg.get("hardfork") or {}).get("heights") or {}
        if "fast_wasm_gas" not in heights:
            raise ValueError(
                "refusing to migrate a version-5 config without an "
                "explicit hardfork.heights.fast_wasm_gas: round-4 nodes "
                "activated the repricing at genesis and the migration "
                "default (never) would silently deactivate it. Set the "
                "height this chain actually activated at (0 for round-4 "
                "devnets) — see DEPLOY.md, 'Upgrading v5 configs'."
            )
    while version < CURRENT_VERSION:
        step = _MIGRATIONS.get(version)
        if step is None:
            raise ValueError(f"no migration from config version {version}")
        cfg = step(cfg)
        version += 1
        cfg["version"] = version
    return cfg


# -- typed sections ----------------------------------------------------------


@dataclass
class NetworkSection:
    host: str = "127.0.0.1"
    port: int = 7070
    # the address OTHER nodes should dial (defaults to host; set when
    # binding a wildcard or behind NAT in multi-host deployments)
    advertise_host: Optional[str] = None
    # public relay(s) — NAT'd nodes with no dialable address participate
    # through one (reference Hub relay bootstrap). A single "host:port:pubhex"
    # string or a LIST of them: the node registers with the first and fails
    # over down the list when its relay stops answering (relay HA)
    relay: Optional[Union[str, List[str]]] = None
    # peers: list of "host:port:pubkeyhex"
    peers: List[str] = field(default_factory=list)


@dataclass
class GenesisSection:
    chain_id: int = 225
    balances: Dict[str, str] = field(default_factory=dict)  # hexaddr -> dec
    # trusted-dealer consensus key set (PublicConsensusKeys.encode() hex) +
    # this node's validator index (-1 = observer)
    consensus_keys: str = ""
    validator_index: int = -1


@dataclass
class VaultSection:
    path: str = "wallet.json"
    password: str = ""


@dataclass
class StakingSection:
    cycle_duration: int = 1000
    vrf_submission_phase: int = 500
    attendance_detection_duration: int = 100


@dataclass
class RpcSection:
    enabled: bool = True
    host: str = "127.0.0.1"
    port: int = 7071
    api_key: Optional[str] = None
    # compressed secp256k1 pubkey hex whose signature unlocks the private
    # RPC methods (reference config "apiKey" doubles as this; kept separate
    # here so the static header key and the signing identity can rotate
    # independently)
    auth_pubkey: Optional[str] = None


@dataclass
class BlockchainSection:
    target_txs_per_block: int = 1000
    target_block_time_ms: int = 1000
    # consensus era pipelining lookahead (DEPLOY.md "Consensus
    # pipelining"): 0 = strictly sequential eras; w >= 1 admits era e+w's
    # proposal/RBC while era e is still in decrypt/commit. Raises journal
    # retention and peak memory by ~w eras — turn off on memory-constrained
    # validators.
    pipeline_window: int = 0


@dataclass
class HardforkSection:
    # name -> activation height (see core/hardforks.py)
    heights: Dict[str, int] = field(default_factory=dict)


@dataclass
class NodeConfig:
    version: int
    network: NetworkSection
    genesis: GenesisSection
    vault: VaultSection
    staking: StakingSection
    rpc: RpcSection
    blockchain: BlockchainSection
    hardfork: HardforkSection
    raw: dict

    @property
    def storage_path(self) -> Optional[str]:
        return self.raw.get("storage", {}).get("path")

    @property
    def storage_engine(self) -> str:
        """"lsm" (the native C++ LSM engine, the default since v7) or
        "sqlite" (explicit opt-out). Configs migrated from <=v6 carry
        engine: "sqlite" pinned by the v6->v7 migration — their database
        was written by sqlite and the formats are not interchangeable.
        Unknown names are a hard error: silently falling back would
        rebuild a fresh chain from genesis on a typo."""
        engine = self.raw.get("storage", {}).get("engine", "lsm")
        if engine not in ("sqlite", "lsm"):
            raise ValueError(
                f"unknown storage.engine {engine!r} (use 'sqlite' or 'lsm')"
            )
        return engine

    @property
    def execution_lanes(self) -> int:
        """Parallel-execution lane count (DEPLOY.md "Parallel execution").
        Optional and additive (no config version bump): 1 pins the serial
        executor, N > 1 fixes the lane count, 0 (the default) sizes lanes
        from the host's cores. Every setting produces bit-identical
        blocks — the knob trades merge/validation overhead against core
        utilization, never semantics."""
        return int(self.raw.get("execution", {}).get("lanes", 0))

    @property
    def merkle_workers(self) -> int:
        """Parallel-merkleization worker count (DEPLOY.md "Parallel
        merkleization"). Optional and additive (no config version bump):
        1 pins the serial walker (deferred batch hashing stays on), N > 1
        fixes the subtrie worker count (capped at the 16-way fanout), 0
        (the default) sizes workers from the host's cores. Every setting
        produces bit-identical state roots — the knob only trades thread
        overhead against core utilization."""
        return int(self.raw.get("execution", {}).get("merkleWorkers", 0))

    @property
    def trace_capacity(self) -> Optional[int]:
        """Flight-recorder ring capacity (events) for BOTH the Python span
        ring and the native engine rings. Optional and additive (no config
        version bump): absent means the LACHAIN_TRACE_CAPACITY env / the
        built-in default decides. 0 disables native recording."""
        cap = self.raw.get("observability", {}).get("traceCapacity")
        return None if cap is None else int(cap)

    @property
    def tx_sample_shift(self) -> Optional[int]:
        """Tx-lifecycle sampling (utils/txtrace.py): keep 1/2^shift of
        transactions (0 = stamp every tx). Optional and additive (no
        config version bump): absent keeps the built-in default. The
        sampling decision itself is a deterministic function of the tx
        hash, but the SHIFT must match fleet-wide for cross-node timelines
        to align (DEPLOY.md "Fleet observability")."""
        shift = self.raw.get("observability", {}).get("txSampleShift")
        return None if shift is None else int(shift)

    @property
    def network_region(self) -> Optional[str]:
        """This node's emulated/labelled WAN region (network.region).
        Optional and additive (no config version bump): used by the
        LinkShaper's region matrix and surfaced in fleet views; absent
        means unlabelled (treated as the shaper's first region when a
        shaper is installed by position)."""
        region = self.raw.get("network", {}).get("region")
        return None if region is None else str(region)

    @property
    def wan_shaper(self) -> Optional[str]:
        """WAN link-shaping spec (network.wanShaper), a LinkShaper.parse
        string like "regions=us,eu;default=40ms/5ms@4mbps;intra=1ms".
        Optional and additive (no config version bump): absent disables
        shaping. The SAME spec (and fault seed) must be installed
        fleet-wide for two-run determinism to hold (DEPLOY.md "WAN
        operations & rolling upgrades")."""
        spec = self.raw.get("network", {}).get("wanShaper")
        return None if spec is None else str(spec)

    @property
    def idle_alert_fraction(self) -> Optional[float]:
        """Idle-anatomy health alert (observability.idleAlertFraction):
        when the rolling era idle fraction from the flight recorder
        exceeds this value, /healthz reads degraded with an idle-fraction
        reason. Optional and additive (no config version bump): absent
        disables the alert."""
        frac = self.raw.get("observability", {}).get("idleAlertFraction")
        return None if frac is None else float(frac)

    @classmethod
    def from_dict(cls, cfg: dict) -> "NodeConfig":
        cfg = migrate(cfg)
        net = cfg.get("network", {})
        gen = cfg.get("genesis", {})
        vault = cfg.get("vault", {})
        staking = cfg.get("staking", {})
        rpc = cfg.get("rpc", {})
        bc = cfg.get("blockchain", {})
        hf = cfg.get("hardfork", {})
        return cls(
            version=cfg["version"],
            network=NetworkSection(
                host=net.get("host", "127.0.0.1"),
                port=int(net.get("port", 7070)),
                advertise_host=net.get("advertiseHost"),
                relay=net.get("relay"),
                peers=list(net.get("peers", [])),
            ),
            genesis=GenesisSection(
                chain_id=int(gen.get("chainId", 225)),
                balances=dict(gen.get("balances", {})),
                consensus_keys=gen.get("consensusKeys", ""),
                validator_index=int(gen.get("validatorIndex", -1)),
            ),
            vault=VaultSection(
                path=vault.get("path", "wallet.json"),
                password=vault.get("password", ""),
            ),
            staking=StakingSection(
                cycle_duration=int(staking.get("cycleDuration", 1000)),
                vrf_submission_phase=int(
                    staking.get("vrfSubmissionPhase", 500)
                ),
                attendance_detection_duration=int(
                    staking.get("attendanceDetectionDuration", 100)
                ),
            ),
            rpc=RpcSection(
                enabled=bool(rpc.get("enabled", True)),
                host=rpc.get("host", "127.0.0.1"),
                port=int(rpc.get("port", 7071)),
                api_key=rpc.get("apiKey"),
                auth_pubkey=rpc.get("authPubkey"),
            ),
            blockchain=BlockchainSection(
                target_txs_per_block=int(bc.get("targetTxsPerBlock", 1000)),
                target_block_time_ms=int(bc.get("targetBlockTimeMs", 1000)),
                pipeline_window=int(bc.get("pipelineWindow", 0)),
            ),
            hardfork=HardforkSection(
                heights={k: int(v) for k, v in hf.get("heights", {}).items()}
            ),
            raw=cfg,
        )

    @classmethod
    def load(cls, path: str) -> "NodeConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.raw, f, indent=2, sort_keys=True)
