"""Block producer: tx proposal, header creation (emulate), block production.

Parity with the reference's BlockProducer
(/root/reference/src/Lachain.Core/Consensus/BlockProducer.cs):
  * GetTransactionsToPropose — Peek(txsPerBlock / N) (73-91)
  * CreateHeader — order txs, emulate, derive state hash (96-183)
  * ProduceBlock — Execute(commit, checkStateHash) (187-220)

This object is handed to RootProtocol (the IBlockProducer seam), keeping the
consensus layer free of chain-state knowledge.
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

from ..utils import txtrace
from ..utils.serialization import Reader, write_bytes_list
from .block_manager import BlockManager
from .tx_pool import TransactionPool
from .types import (
    Block,
    BlockHeader,
    MultiSig,
    SignedTransaction,
    tx_merkle_root,
)

DEFAULT_TXS_PER_BLOCK = 1000  # reference BlockProducer.cs:69


def encode_tx_batch(txs: Sequence[SignedTransaction]) -> bytes:
    """Wire form of a proposal (the RawShare payload fed into HoneyBadger)."""
    return write_bytes_list([t.encode() for t in txs])


# decoded-proposal memo: in-process multi-validator harnesses hand the SAME
# proposal bytes to every validator (N=64 -> 64x64 identical decodes per
# era), and sharing the immutable SignedTransaction objects also shares
# their hash/sender caches. Bounded FIFO keyed by the raw wire bytes.
_DECODE_MEMO: dict = {}
_DECODE_MEMO_MAX = 256


def decode_tx_batch(data: bytes) -> List[SignedTransaction]:
    cached = _DECODE_MEMO.get(data)
    if cached is None:
        r = Reader(data)
        cached = tuple(SignedTransaction.decode(b) for b in r.bytes_list())
        r.assert_eof()
        if len(_DECODE_MEMO) >= _DECODE_MEMO_MAX:
            _DECODE_MEMO.pop(next(iter(_DECODE_MEMO)))
        _DECODE_MEMO[data] = cached
    return list(cached)


class BlockProducer:
    def __init__(
        self,
        block_manager: BlockManager,
        pool: TransactionPool,
        n_validators: int,
        txs_per_block: int = DEFAULT_TXS_PER_BLOCK,
        proposal_seed: int = -1,
    ):
        self.bm = block_manager
        self.pool = pool
        self.n = n_validators
        self.txs_per_block = txs_per_block
        # per-validator randomized proposals (RandomSamplingQueue role):
        # HB blocks carry the union of n proposals, so identical top-fee
        # picks would cap blocks at txs_per_block / n distinct txs
        self.proposal_seed = proposal_seed
        # pipelined-proposal overlay: when era e+1 proposes while era e's
        # block is decided but not yet committed, the proposal must behave
        # as if that block had already landed — same rng height, no tx
        # claimed by an in-flight block, per-sender nonces advanced past
        # the in-flight ones. The window scheduler installs it before the
        # proposal and clears it when the window drains.
        self._ov_height: Optional[int] = None
        self._ov_exclude: set = set()
        self._ov_nonces: dict = {}

    # -- proposal ---------------------------------------------------------------
    def pipeline_overlay_push(
        self, height: int, txs: Sequence[SignedTransaction], chain_id: int
    ) -> None:
        """Extend the overlay with one in-flight block: proposals now build
        on virtual height `height` (the next block index) and skip `txs`.
        Cumulative — called once per decided-but-uncommitted era."""
        self._ov_height = height
        for stx in txs:
            self._ov_exclude.add(stx.hash())
            sender = stx.sender(chain_id)
            if sender is None:
                continue
            nxt = stx.tx.nonce + 1
            if nxt > self._ov_nonces.get(sender, 0):
                self._ov_nonces[sender] = nxt

    def pipeline_overlay_clear(self) -> None:
        self._ov_height = None
        self._ov_exclude = set()
        self._ov_nonces = {}

    def get_transactions_to_propose(self) -> List[SignedTransaction]:
        height = (
            self._ov_height
            if self._ov_height is not None
            else self.bm.current_height()
        )
        rng = (
            random.Random((self.proposal_seed << 20) ^ height)
            if self.proposal_seed >= 0
            else None
        )
        txs = self.pool.peek(
            max(self.txs_per_block // max(self.n, 1), 1),
            rng=rng,
            window_txs=2 * self.txs_per_block,
            exclude=self._ov_exclude if self._ov_exclude else None,
            nonce_override=self._ov_nonces if self._ov_nonces else None,
        )
        # tx lifecycle: these txs ride OUR proposal for era height+1
        # (sampled-only; first stamp wins across repeated proposals)
        txtrace.stamp_many(
            (stx.hash() for stx in txs), "propose", era=height + 1
        )
        return txs

    # -- header -----------------------------------------------------------------
    def create_header(
        self, index: int, txs: Sequence[SignedTransaction], nonce: int
    ) -> BlockHeader:
        prev = self.bm.block_by_height(index - 1)
        if prev is None:
            raise ValueError(f"no parent block at height {index - 1}")
        ordered = self.bm.order_transactions(txs, self.bm.executer.chain_id)
        em = self.bm.emulate(ordered, index)
        return BlockHeader(
            index=index,
            prev_block_hash=prev.hash(),
            merkle_root=tx_merkle_root([t.hash() for t in ordered]),
            state_hash=em.state_hash,
            nonce=nonce,
        )

    # -- production -------------------------------------------------------------
    def produce_block(
        self,
        header: BlockHeader,
        txs: Sequence[SignedTransaction],
        multisig: MultiSig,
    ) -> Block:
        block = self.bm.execute_block(
            header, txs, multisig, check_state_hash=True
        )
        self.pool.remove_included(block.tx_hashes)
        self.pool.sanitize()
        return block
