"""Optimistic lane-parallel block execution (the block-STM shape).

The reference executes every transaction serially inside
BlockManager._Execute (/root/reference/src/Lachain.Core/Blockchain/
Operations/BlockManager.cs:371-560). This module keeps that executor as
the semantic oracle and adds an optimistic-concurrency path over it:

  1. PLAN   — partition the canonically-ordered block into lanes by
     touched-account footprint (sender / recipient, union-find over the
     static footprint). Same-sender nonce chains share the sender address
     so they land in one lane by construction; every tx paying one
     recipient, or calling one system contract, coalesces the same way.
  2. RUN    — execute each lane concurrently against its own Snapshot
     over a forked Trie (Trie.fork: shared kv, private cache/pending),
     all based on the SAME immutable base StateRoots. A RecordingSnapshot
     logs, per tx, every externally-observed read (key -> value seen) and
     the tx's surviving write delta.
  3. MERGE  — walk the transactions back in canonical order against one
     merged snapshot on the main trie. A tx whose recorded reads all
     still match the merged state provably executed exactly as the serial
     oracle would have (execution is a deterministic function of the tx
     and its observed reads), so its recorded delta and receipt are taken
     verbatim. Any mismatch makes the tx a STRAGGLER: it re-executes
     serially on the merged snapshot at its canonical position — which IS
     serial execution for that tx.

Bit-identity argument (pinned by tests/test_parallel_exec.py): by
induction over canonical index i, the merged snapshot before tx_i equals
the serial executor's state before tx_i. Validated tx_i observed exactly
the values the serial executor would read, so its writes/receipt are the
serial ones; a straggler literally runs the serial executor. Hence
receipts, the final write-set, the frozen roots AND the trie node set
(freeze applies an identical write map through Trie.apply_many) are all
bit-identical to the serial pass. Each tx re-executes at most once, so a
forced-100%-conflict workload degrades to exactly one serial pass plus
the (wasted) lane pass — graceful, never a livelock.

On a single hardware thread the lanes buy no wall-clock (pure-Python
execution under the GIL); the win there comes from the delta-checkpoint
snapshot and the commit-path work this PR removes. On multi-core hosts
the lanes overlap trie reads, keccak hashing and wasm interpretation,
which all release the GIL in their native sections.
"""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..storage.state import Snapshot, StateManager, StateRoots
from ..utils import metrics
from .execution import TransactionExecuter
from .types import SignedTransaction, TransactionReceipt, warm_sender_caches

# lanes=0 in config means "auto": one lane per core, clamped — beyond 8
# lanes the merge walk and fork setup outweigh extra overlap
_AUTO_LANE_CAP = 8
# blocks smaller than this execute serially even when lanes are enabled:
# fork + merge overhead beats any overlap win on tiny blocks
MIN_PARALLEL_TXS = 32


def resolve_lanes(configured: int) -> int:
    """Map the execution.lanes knob to an effective lane count:
    1 pins serial, N>1 is explicit, 0 = auto (cores, capped)."""
    if configured >= 1:
        return configured
    return max(1, min(_AUTO_LANE_CAP, os.cpu_count() or 1))


class RecordingSnapshot(Snapshot):
    """Snapshot that records, per transaction, the read/write footprint
    the merge phase validates against.

    reads: (tree, key) -> value observed, recorded only when the value
      came from OUTSIDE the tx (base state or earlier same-lane txs) —
      reads of the tx's own live writes carry no external dependency.
    own:   (tree, key) -> live-write count; a count > 0 at end_tx means
      the tx left a net write on the key (rolled-back writes decay to 0
      through the undo hook below), and the key's final buffered value
      joins the delta.
    """

    def __init__(self, trie, roots: StateRoots):
        super().__init__(trie, roots)
        self._reads: Dict[Tuple[str, bytes], Optional[bytes]] = {}
        self._own: Dict[Tuple[str, bytes], int] = {}

    def begin_tx(self) -> None:
        self._reads = {}
        self._own = {}

    def end_tx(self):
        """-> (reads, delta): the validation footprint and the surviving
        buffer writes of the tx just executed."""
        writes = self._writes
        delta = [
            (tree, key, writes[tree][key])
            for (tree, key), live in self._own.items()
            if live > 0
        ]
        return self._reads, delta

    # -- recording overrides -------------------------------------------------
    def get(self, tree: str, key: bytes) -> Optional[bytes]:
        buf = self._writes[tree]
        if key in buf:
            v = buf[key]
        else:
            v = self._trie.get(getattr(self.base, tree), key)
        rk = (tree, key)
        if rk not in self._reads and not self._own.get(rk):
            # first externally-visible observation wins; later reads either
            # repeat it (pre-tx state is immutable during the tx) or see the
            # tx's own writes (no dependency)
            self._reads[rk] = v
        return v

    def put(self, tree: str, key: bytes, value: bytes) -> None:
        super().put(tree, key, value)
        rk = (tree, key)
        self._own[rk] = self._own.get(rk, 0) + 1

    def delete(self, tree: str, key: bytes) -> None:
        super().delete(tree, key)
        rk = (tree, key)
        self._own[rk] = self._own.get(rk, 0) + 1

    def restore(self, cp: int) -> None:
        # rolled-back writes must not count as live own-writes, or a
        # reverted tx would export a no-op delta that could clobber an
        # interleaved lane's write at merge time
        popped = self._undo[cp:]
        super().restore(cp)
        own = self._own
        for tree, key, _prior in popped:
            rk = (tree, key)
            live = own.get(rk, 0) - 1
            if live > 0:
                own[rk] = live
            else:
                own.pop(rk, None)


# -- lane planning ------------------------------------------------------------


def _footprint_groups(
    ordered: Sequence[SignedTransaction], chain_id: int
) -> List[bytes]:
    """Union-find over each tx's static account footprint (sender +
    recipient); returns each tx's resolved group root. Two txs share a
    group iff their footprints are transitively connected — the
    no-false-negative partition for the simple-transfer / system-contract
    surface (wasm cross-contract effects are caught by merge validation,
    not by planning)."""
    parent: Dict[bytes, bytes] = {}

    def find(a: bytes) -> bytes:
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    tx_key: List[bytes] = []
    for stx in ordered:
        sender = stx.sender(chain_id)
        keys = [stx.tx.to] if sender is None else [sender, stx.tx.to]
        for k in keys:
            if k not in parent:
                parent[k] = k
        head = find(keys[0])
        for k in keys[1:]:
            r = find(k)
            if r != head:
                parent[r] = head
        tx_key.append(keys[0])
    return [find(k) for k in tx_key]


def plan_lanes(
    ordered: Sequence[SignedTransaction],
    chain_id: int,
    n_lanes: int,
    partition: Optional[Callable[[int, SignedTransaction], int]] = None,
) -> List[List[Tuple[int, SignedTransaction]]]:
    """Deterministic lane assignment for a canonically-ordered block:
    footprint groups packed greedily (largest first, ties by first
    appearance) onto the least-loaded lane; canonical order is preserved
    WITHIN each lane. `partition` overrides the group rule (tests use it
    to force conflicting txs apart)."""
    if n_lanes <= 1:
        return [list(enumerate(ordered))]
    lanes: List[List[Tuple[int, SignedTransaction]]] = [
        [] for _ in range(n_lanes)
    ]
    if partition is not None:
        for i, stx in enumerate(ordered):
            lanes[partition(i, stx) % n_lanes].append((i, stx))
        return lanes
    groups = _footprint_groups(ordered, chain_id)
    sizes: Dict[bytes, int] = {}
    first: Dict[bytes, int] = {}
    for i, g in enumerate(groups):
        sizes[g] = sizes.get(g, 0) + 1
        first.setdefault(g, i)
    load = [0] * n_lanes
    lane_of: Dict[bytes, int] = {}
    for g in sorted(sizes, key=lambda g: (-sizes[g], first[g])):
        lane = min(range(n_lanes), key=lambda l: load[l])
        lane_of[g] = lane
        load[lane] += sizes[g]
    for i, stx in enumerate(ordered):
        lanes[lane_of[groups[i]]].append((i, stx))
    return lanes


# -- execution ----------------------------------------------------------------


@dataclass
class ParallelStats:
    """Per-block parallel-execution report (also pushed to metrics)."""

    lanes: int
    txs: int
    validated: int
    stragglers: int
    lane_sizes: List[int] = field(default_factory=list)

    @property
    def conflict_rate(self) -> float:
        return self.stragglers / self.txs if self.txs else 0.0


def execute_block_parallel(
    executer: TransactionExecuter,
    state: StateManager,
    ordered: Sequence[SignedTransaction],
    block_index: int,
    base_roots: StateRoots,
    n_lanes: int,
    partition: Optional[Callable[[int, SignedTransaction], int]] = None,
) -> Tuple[Snapshot, List[TransactionReceipt], ParallelStats]:
    """Run an ordered block through the lane/merge pipeline; returns the
    merged (un-frozen) snapshot on the main trie, the receipts in
    canonical order, and the stats. The caller freezes — exactly where
    the serial path freezes — so the two paths share the commit seam."""
    chain_id = executer.chain_id
    warm_sender_caches(ordered, chain_id)
    lanes = [l for l in plan_lanes(ordered, chain_id, n_lanes, partition) if l]

    def run_lane(lane: List[Tuple[int, SignedTransaction]]):
        snap = RecordingSnapshot(state.trie.fork(), base_roots)
        out = []
        for gi, stx in lane:
            snap.begin_tx()
            res = executer.execute(snap, stx, block_index, gi)
            reads, delta = snap.end_tx()
            out.append((gi, res.receipt, reads, delta))
        return out

    if len(lanes) <= 1:
        lane_results = [run_lane(lane) for lane in lanes]
    else:
        with ThreadPoolExecutor(
            max_workers=len(lanes), thread_name_prefix="exec-lane"
        ) as pool:
            lane_results = list(pool.map(run_lane, lanes))

    by_index: Dict[int, tuple] = {}
    for lane_out in lane_results:
        for rec in lane_out:
            by_index[rec[0]] = rec

    # canonical-order merge with read validation; stragglers re-execute
    # serially on the merged snapshot (<= one serial pass in total)
    merged = state.new_snapshot(base_roots)
    merged_writes = merged._writes
    receipts: List[TransactionReceipt] = []
    stragglers = 0
    for i, stx in enumerate(ordered):
        _, receipt, reads, delta = by_index[i]
        ok = True
        for (tree, key), seen in reads.items():
            if merged.get(tree, key) != seen:
                ok = False
                break
        if ok:
            for tree, key, value in delta:
                merged_writes[tree][key] = value
            receipts.append(receipt)
        else:
            stragglers += 1
            res = executer.execute(merged, stx, block_index, i)
            receipts.append(res.receipt)

    stats = ParallelStats(
        lanes=len(lanes),
        txs=len(ordered),
        validated=len(ordered) - stragglers,
        stragglers=stragglers,
        lane_sizes=[len(l) for l in lanes],
    )
    metrics.set_gauge("exec_lanes", stats.lanes)
    metrics.set_gauge("exec_conflict_rate", stats.conflict_rate)
    metrics.inc("exec_txs_validated_total", stats.validated)
    metrics.inc("exec_txs_straggler_total", stats.stragglers)
    metrics.inc("exec_blocks_parallel_total")
    return merged, receipts, stats
