"""Fast state sync: multi-peer trie-node download instead of block replay.

Parity with the reference's fast synchronizer
(/root/reference/src/Lachain.Core/Network/FastSynchronizerBatch.cs:13-50,
StateDownloader.cs:1-316, RequestManager.cs:1-174): a fresh node fetches the
STATE at a recent height directly — here node-by-node from the
content-addressed trie — and only then follows the chain normally.

The content-addressed redesign makes the download TRUSTLESS at the node
level: every received node must hash (keccak256) to the hash that requested
it, so a malicious peer cannot substitute state. Trust roots:

  * the target block's validator multisig is checked against a key set the
    syncing node knows — the genesis set by default, or an operator-supplied
    (height, block_hash) checkpoint when the chain has rotated validators
    (the reference has the same bootstrap assumption: a fresh node cannot
    verify deep rotations without replaying them)
  * the downloaded roots must hash to the block header's state_hash

Download scheduler (reference RequestManager.cs): a bounded BFS frontier
feeds up to `max_inflight` concurrent batches spread across every live
serving peer. Each request carries a request id, so a late or duplicated
reply can never be attributed to the wrong batch. A timed-out batch is
requeued and retried against a different peer (the failed peer backs off
with seeded jitter); a peer that serves a node not hashing to its request
is banned for the session; a peer that times out repeatedly is declared
dead. The sync only fails when no live serving peer remains.

Frontier memory is bounded: at most `frontier_cap` discovered-but-not-
fetched hashes are held in RAM, the overflow is spilled to KV rows
(EntryPrefix.FASTSYNC_FRONTIER) and restored as memory drains, so a
100k+-node trie syncs in O(cap) frontier memory. (The dedup set of seen
hashes is 32 bytes per node and stays in RAM.)

Bulk path (`snapshot=True`): before the trie walk, pull the peer's whole
trie-node keyspace in cursor-addressed pages (resumable from any other
peer mid-stream — the cursor is just the last node hash), import the
records content-addressed, then run the normal walk over the (ideally
empty) diff. A snapshot can never poison state: records that do not hash
correctly are unreachable garbage, and the walk re-downloads whatever
the snapshot missed — node-by-node fallback is the walk itself.
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from ..crypto.hashes import keccak256
from ..network import wire
from ..storage.kv import EntryPrefix, prefixed
from ..utils import metrics
from ..storage.state import StateRoots
from ..storage.trie import EMPTY_ROOT, InternalNode, _decode as _trie_decode
from .synchronizer import verify_block_multisig
from .types import Block

logger = logging.getLogger(__name__)

BATCH = 256  # node hashes per request (reference batch download workers)
FRONTIER_CAP = 4096  # in-memory frontier hashes before spilling to KV
HASH_LEN = 32


class BoundedFrontier:
    """BFS frontier with bounded resident memory.

    At most `cap` hashes live in the in-memory deque; overflow spills to
    KV rows under EntryPrefix.FASTSYNC_FRONTIER (chunked, newest-first)
    and is restored as the deque drains. Rows are deleted on restore and
    `clear()` removes the whole keyspace on sync completion — leftovers
    after a mid-sync crash are repairable garbage that fsck prunes.
    """

    def __init__(self, kv, cap: int = FRONTIER_CAP, chunk: int = 2048):
        self.kv = kv
        self.cap = max(2, cap)
        self.chunk = max(1, min(chunk, self.cap // 2))
        self._mem: Deque[bytes] = deque()
        self._seen = set()
        self._lo = 0  # [lo, hi) = live spill row ids
        self._hi = 0
        self._spilled = 0
        self.peak = 0  # max resident frontier size (the bounded claim)
        self.spilled_total = 0

    def __len__(self) -> int:
        return len(self._mem) + self._spilled

    @staticmethod
    def _row_key(idx: int) -> bytes:
        return prefixed(EntryPrefix.FASTSYNC_FRONTIER, idx.to_bytes(8, "big"))

    def push(self, h: bytes) -> None:
        if h in self._seen:
            return
        self._seen.add(h)
        self._mem.append(h)
        self._overflow()

    def requeue(self, hashes: List[bytes]) -> None:
        """Retry path: hashes already seen but still unfetched go back to
        the FRONT so a failed batch is retried before new discoveries."""
        self._mem.extendleft(reversed(hashes))
        self._overflow()

    def pop_many(self, n: int) -> List[bytes]:
        out: List[bytes] = []
        while len(out) < n:
            if not self._mem and not self._restore():
                break
            out.append(self._mem.popleft())
        return out

    def _overflow(self) -> None:
        while len(self._mem) > self.cap:
            take = min(self.chunk, len(self._mem) - self.cap // 2)
            batch = [self._mem.pop() for _ in range(take)]
            self.kv.put(self._row_key(self._hi), b"".join(batch))
            self._hi += 1
            self._spilled += take
            self.spilled_total += take
            metrics.inc("fastsync_frontier_spilled_total", take)
        self.peak = max(self.peak, len(self._mem))

    def _restore(self) -> bool:
        if self._spilled == 0:
            return False
        self._hi -= 1  # newest row first: depth-first drain of the spill
        key = self._row_key(self._hi)
        data = self.kv.get(key) or b""
        self.kv.delete(key)
        hashes = [
            data[i : i + HASH_LEN] for i in range(0, len(data), HASH_LEN)
        ]
        self._spilled -= len(hashes)
        self._mem.extend(hashes)
        self.peak = max(self.peak, len(self._mem))
        return bool(hashes)

    def clear(self) -> None:
        for i in range(self._lo, self._hi):
            self.kv.delete(self._row_key(i))
        self._lo = self._hi = self._spilled = 0
        self._mem.clear()
        self._seen.clear()


@dataclass
class PeerScore:
    """Per-session serving-peer scoreboard (mirrored into labeled
    fastsync_peer_* metrics)."""

    served: int = 0
    timeouts: int = 0
    bad_nodes: int = 0
    misses: int = 0
    banned: bool = False
    dead: bool = False
    consecutive_failures: int = 0
    backoff_until: float = 0.0

    def live(self) -> bool:
        return not (self.banned or self.dead)


@dataclass
class _Request:
    peer: bytes
    hashes: List[bytes]
    deadline: float


def _plabel(pub: bytes) -> Dict[str, str]:
    return {"peer": pub.hex()[:16]}


class FastSynchronizer:
    def __init__(
        self,
        node,
        *,
        trusted: Optional[Tuple[int, bytes]] = None,
        batch: int = BATCH,
    ):
        """`node`: the owning core.node.Node. `trusted`: optional
        (height, block_hash) checkpoint that overrides multisig
        verification for the target block."""
        self.node = node
        self.trusted = trusted
        self.batch = batch
        # scheduler knobs (tests and operators tune these)
        self.max_inflight = 4
        self.frontier_cap = FRONTIER_CAP
        self.request_timeout = 5.0
        self.backoff_base = 0.5
        self.backoff_cap = 10.0
        self.peer_death_threshold = 4
        # serving-side token bucket, in trie nodes (not requests): refills
        # serve_rate nodes/s per sender up to serve_capacity burst
        self.serve_rate = 4096.0
        self.serve_capacity = 8192.0
        self.snapshot_page = 4096  # records per snapshot pull page
        self.snapshot_max_bytes = 4 << 20  # byte cap per page
        self._serve_buckets: Dict[bytes, Tuple[float, float]] = {}
        # seeded jitter: deterministic per node identity, like the worker
        # reconnect backoff
        self._rng = random.Random(zlib.crc32(node.network.public_key))
        # block/roots phase (single outstanding request to self._peer)
        self._reply: Optional[Tuple[Optional[Block], bytes]] = None
        self._peer: Optional[bytes] = None
        self._reply_event = asyncio.Event()
        # download scheduler state
        self._inflight: Dict[int, _Request] = {}
        self._next_rid = 1
        self._replies: Deque[Tuple[bytes, int, List[bytes]]] = deque()
        self._snap_replies: Deque[tuple] = deque()
        self._wake = asyncio.Event()
        self._scores: Dict[bytes, PeerScore] = {}
        self._frontier: Optional[BoundedFrontier] = None
        self._rr = 0
        net = node.network
        net.on_fast_sync_request = self._serve_fast_sync
        net.on_fast_sync_reply = self._on_fast_sync_reply
        net.on_trie_nodes_request = self._serve_trie_nodes
        net.on_trie_nodes_reply = self._on_trie_nodes_reply
        net.on_trie_nodes_request_id = self._serve_trie_nodes_id
        net.on_trie_nodes_reply_id = self._on_trie_nodes_reply_id
        net.on_snapshot_request = self._serve_snapshot
        net.on_snapshot_reply = self._on_snapshot_reply

    # -- serving side --------------------------------------------------------

    def _serve_allow(self, sender: bytes, cost: float) -> bool:
        """Per-sender token bucket (the message_request replay limiter
        shape): a request costs its node count, so the limiter bounds KV
        read work, not just request count. Over-budget requests are
        dropped — the client's retry/failover path handles it like loss."""
        now = time.monotonic()
        tokens, last = self._serve_buckets.get(
            sender, (self.serve_capacity, now)
        )
        tokens = min(
            self.serve_capacity, tokens + (now - last) * self.serve_rate
        )
        if len(self._serve_buckets) > 4096:
            self._serve_buckets.clear()
        if tokens < cost:
            self._serve_buckets[sender] = (tokens, now)
            metrics.inc("fastsync_serve_throttled_total")
            return False
        self._serve_buckets[sender] = (tokens - cost, now)
        return True

    def _serve_fast_sync(self, sender: bytes, height: int) -> None:
        bm = self.node.block_manager
        if height == 0:
            height = bm.current_height()
        block = bm.block_by_height(height)
        roots = self.node.state.roots_at(height)
        if block is None or roots is None:
            self.node.network.send_to(sender, wire.fast_sync_reply(None, b""))
            return
        self.node.network.send_to(
            sender, wire.fast_sync_reply(block, roots.encode())
        )

    def _lookup_nodes(self, hashes: List[bytes]) -> List[bytes]:
        kv = self.node.kv
        out = []
        for h in hashes:
            enc = kv.get(prefixed(EntryPrefix.TRIE_NODE, h))
            if enc is not None:
                out.append(enc)
        return out

    def _serve_trie_nodes(self, sender: bytes, hashes: List[bytes]) -> None:
        # id-less kind, kept for older peers; same throttle as the id path
        hashes = hashes[: 4 * self.batch]
        if not self._serve_allow(sender, len(hashes)):
            return
        self.node.network.send_to(
            sender, wire.trie_nodes_reply(self._lookup_nodes(hashes))
        )

    def _serve_trie_nodes_id(
        self, sender: bytes, rid: int, hashes: List[bytes]
    ) -> None:
        hashes = hashes[: 4 * self.batch]
        if not self._serve_allow(sender, len(hashes)):
            return
        self.node.network.send_to(
            sender, wire.trie_nodes_reply_id(rid, self._lookup_nodes(hashes))
        )

    def _serve_snapshot(
        self, sender: bytes, rid: int, cursor: bytes, limit: int
    ) -> None:
        limit = max(1, min(limit, 8192))
        if not self._serve_allow(sender, limit):
            return
        prefix = prefixed(EntryPrefix.TRIE_NODE)
        rows = self.node.kv.scan_from(prefix, cursor, limit + 1)
        included: List[Tuple[bytes, bytes]] = []
        total = 0
        for k, v in rows[:limit]:
            if included and total + len(v) > self.snapshot_max_bytes:
                break
            included.append((k, v))
            total += len(v)
        done = len(included) == len(rows)
        next_cursor = included[-1][0][2:] if included else cursor
        self.node.network.send_to(
            sender,
            wire.snapshot_reply(
                rid, next_cursor, done, [v for _, v in included]
            ),
        )

    # -- client side ---------------------------------------------------------

    def _on_fast_sync_reply(self, sender, block, roots_enc) -> None:
        # only the peer we asked, and only while a request is in flight —
        # any other connected peer could otherwise inject a stale-but-signed
        # snapshot (pinning a fresh node asking for height=0 to old state)
        # or poison the node download into a spurious abort
        if self._peer is None or sender != self._peer or self._reply_event.is_set():
            return
        self._reply = (block, roots_enc)
        self._reply_event.set()

    def _on_trie_nodes_reply(self, sender, nodes: List[bytes]) -> None:
        # the id-less reply kind is never requested by this client anymore;
        # anything arriving here is late traffic from an abandoned exchange —
        # exactly the reply class that used to be consumed as the current
        # batch's answer and abort the sync
        metrics.inc("fastsync_stale_replies_total")

    def _on_trie_nodes_reply_id(
        self, sender: bytes, rid: int, nodes: List[bytes]
    ) -> None:
        if rid not in self._inflight:
            metrics.inc("fastsync_stale_replies_total")
            return
        self._replies.append((sender, rid, nodes))
        self._wake.set()

    def _on_snapshot_reply(
        self, sender: bytes, rid: int, next_cursor: bytes, done: bool, records
    ) -> None:
        self._snap_replies.append((sender, rid, next_cursor, done, records))
        self._wake.set()

    # -- scoreboard ----------------------------------------------------------

    def _score(self, pub: bytes) -> PeerScore:
        s = self._scores.get(pub)
        if s is None:
            s = self._scores[pub] = PeerScore()
        return s

    @property
    def scoreboard(self) -> Dict[bytes, PeerScore]:
        """Per-peer serving stats for the current/most recent session."""
        return dict(self._scores)

    def _live(self, pub: bytes) -> bool:
        return self._score(pub).live()

    def _backoff(self, s: PeerScore) -> None:
        base = self.backoff_base * (2 ** min(s.consecutive_failures - 1, 5))
        jitter = 0.75 + 0.5 * self._rng.random()
        s.backoff_until = time.monotonic() + min(
            self.backoff_cap, base * jitter
        )

    def _penalize(self, pub: bytes, *, timeout: bool) -> None:
        s = self._score(pub)
        s.consecutive_failures += 1
        if timeout:
            s.timeouts += 1
            metrics.inc("fastsync_request_timeouts_total")
            metrics.inc("fastsync_peer_timeouts_total", labels=_plabel(pub))
        self._backoff(s)
        if s.consecutive_failures >= self.peer_death_threshold and not s.dead:
            s.dead = True
            logger.warning(
                "fast sync: peer %s unresponsive after %d failures, "
                "failing over to remaining peers",
                pub.hex()[:16],
                s.consecutive_failures,
            )

    def _ban(self, pub: bytes, bad: int) -> None:
        s = self._score(pub)
        s.bad_nodes += bad
        metrics.inc(
            "fastsync_peer_bad_nodes_total", bad, labels=_plabel(pub)
        )
        if not s.banned:
            s.banned = True
            metrics.inc("fastsync_peer_banned_total", labels=_plabel(pub))
            logger.warning(
                "fast sync: peer %s served %d nodes not hashing to their "
                "request — banned for this session",
                pub.hex()[:16],
                bad,
            )

    # -- sync orchestration --------------------------------------------------

    async def sync(
        self,
        peers,
        height: int = 0,
        timeout: float = 60.0,
        *,
        snapshot: bool = False,
    ) -> int:
        """Download the state at `height` (0 = serving peers' tip) from
        `peers` — one ECDSA pubkey or a list of them. Returns the synced
        height. Raises on verification failure, or when no live serving
        peer remains. `timeout` bounds the block/roots handshake; batch
        pacing is governed by `request_timeout`/backoff."""
        if isinstance(peers, (bytes, bytearray)):
            peers = [bytes(peers)]
        peers = list(dict.fromkeys(bytes(p) for p in peers))
        if not peers:
            raise ValueError("fast sync needs at least one serving peer")
        self._scores = {p: PeerScore() for p in peers}
        self._inflight.clear()
        self._replies.clear()
        self._snap_replies.clear()
        self._reply = None
        try:
            return await self._sync_inner(peers, height, timeout, snapshot)
        finally:
            self._peer = None  # stop accepting replies once the sync ends
            self._inflight.clear()

    async def _sync_inner(
        self, peers: List[bytes], height: int, timeout: float, snapshot: bool
    ) -> int:
        node = self.node
        block, roots_enc = None, b""
        # block/roots handshake: ask peers one at a time until one answers
        per_peer = max(1.0, timeout / max(1, len(peers)))
        for p in peers:
            self._reply = None
            self._peer = p
            self._reply_event.clear()
            node.network.send_to(p, wire.fast_sync_request(height))
            try:
                await asyncio.wait_for(self._reply_event.wait(), per_peer)
            except asyncio.TimeoutError:
                self._penalize(p, timeout=True)
                continue
            block, roots_enc = self._reply or (None, b"")
            if block is not None:
                break
            self._score(p).misses += 1
        self._peer = None
        if block is None:
            raise ValueError("peer served no fast-sync snapshot")
        target = block.header.index
        roots = StateRoots.decode(roots_enc)
        if roots.state_hash() != block.header.state_hash:
            raise ValueError("fast-sync roots do not match the block header")
        if self.trusted is not None:
            t_height, t_hash = self.trusted
            if target != t_height or block.hash() != t_hash:
                raise ValueError("fast-sync block differs from checkpoint")
        elif not verify_block_multisig(
            block, node.validator_manager.genesis_keys
        ):
            raise ValueError(
                "fast-sync block lacks a known-validator quorum "
                "(provide a trusted checkpoint for rotated chains)"
            )

        if snapshot:
            complete = await self._import_snapshot(peers)
            if not complete:
                logger.warning(
                    "fast sync: snapshot import incomplete — "
                    "falling back to node-by-node download"
                )
        downloaded = await self._download_nodes(peers, roots)
        # install: state + block + height index (the block itself, so the
        # chain links for subsequent normal sync; tx bodies are not needed)
        node.kv.write_batch(
            [
                (
                    prefixed(EntryPrefix.BLOCK_BY_HASH, block.hash()),
                    block.encode(),
                ),
                (
                    prefixed(
                        EntryPrefix.BLOCK_HASH_BY_HEIGHT,
                        wire.write_u64(target),
                    ),
                    block.hash(),
                ),
            ]
        )
        node.state.commit(target, roots)
        logger.info(
            "fast sync complete: height %d, %d trie nodes downloaded, "
            "frontier peak %d",
            target,
            downloaded,
            self._frontier.peak if self._frontier else 0,
        )
        return target

    # -- bulk path: cursor-paged snapshot pull -------------------------------

    async def _import_snapshot(self, peers: List[bytes]) -> bool:
        """Pull the serving peers' trie-node keyspace page by page and
        import it content-addressed. Resumes at the cursor from another
        peer on timeout. Returns False (caller falls back to the plain
        walk) when no live peer remains or a page makes no progress."""
        kv = self.node.kv
        cursor = b""
        while True:
            now = time.monotonic()
            candidates = [
                p
                for p in peers
                if self._live(p) and self._score(p).backoff_until <= now
            ]
            if not candidates:
                if not any(self._live(p) for p in peers):
                    return False
                await asyncio.sleep(0.05)
                continue
            self._rr += 1
            peer = candidates[self._rr % len(candidates)]
            rid = self._next_rid
            self._next_rid += 1
            self.node.network.send_to(
                peer, wire.snapshot_request(rid, cursor, self.snapshot_page)
            )
            reply = await self._wait_snapshot_reply(peer, rid)
            if reply is None:
                self._penalize(peer, timeout=True)
                metrics.inc("fastsync_failovers_total")
                continue  # same cursor, next candidate peer
            next_cursor, done, records = reply
            puts = []
            bad = 0
            for enc in records:
                try:
                    _trie_decode(enc)
                except Exception:
                    bad += 1
                    continue
                puts.append(
                    (prefixed(EntryPrefix.TRIE_NODE, keccak256(enc)), enc)
                )
            if bad:
                self._ban(peer, bad)
                continue
            if records and next_cursor <= cursor and not done:
                # a page must advance the cursor; a peer stuck in place
                # would loop the import forever
                self._penalize(peer, timeout=False)
                continue
            if puts:
                kv.ingest(puts)
            s = self._score(peer)
            s.served += len(puts)
            s.consecutive_failures = 0
            s.backoff_until = 0.0
            metrics.inc("fastsync_snapshot_records_total", len(puts))
            metrics.inc("fastsync_snapshot_pages_total")
            metrics.inc(
                "fastsync_peer_served_total", len(puts), labels=_plabel(peer)
            )
            if done:
                return True
            if not records:
                return False
            cursor = next_cursor

    async def _wait_snapshot_reply(self, peer: bytes, rid: int):
        deadline = time.monotonic() + self.request_timeout
        while True:
            while self._snap_replies:
                sender, r, next_cursor, done, records = (
                    self._snap_replies.popleft()
                )
                if r != rid or sender != peer:
                    metrics.inc("fastsync_stale_replies_total")
                    continue
                return next_cursor, done, records
            delay = deadline - time.monotonic()
            if delay <= 0:
                return None
            try:
                await asyncio.wait_for(self._wake.wait(), delay)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    # -- node-by-node path: bounded frontier + request scheduler -------------

    async def _download_nodes(
        self, peers: List[bytes], roots: StateRoots
    ) -> int:
        """BFS over missing nodes: up to max_inflight request-id batches
        spread across live peers, every node hash-verified, timed-out
        batches requeued against other peers. Naturally resumable: nodes
        already in the KV are skipped."""
        kv = self.node.kv
        frontier = BoundedFrontier(kv, self.frontier_cap)
        self._frontier = frontier
        for r in roots.all_roots():
            if r != EMPTY_ROOT:
                frontier.push(r)
        downloaded = 0
        while len(frontier) or self._inflight:
            now = time.monotonic()
            self._expire_requests(frontier, now)
            live = [p for p in peers if self._live(p)]
            if not live:
                raise ValueError(
                    "fast sync aborted: no live serving peers remain"
                )
            while len(self._inflight) < self.max_inflight and len(frontier):
                want = self._next_batch(frontier, kv)
                if not want:
                    break
                peer = self._pick_peer(live, time.monotonic())
                if peer is None:  # every live peer is backing off
                    frontier.requeue(want)
                    break
                rid = self._next_rid
                self._next_rid += 1
                self._inflight[rid] = _Request(
                    peer, want, time.monotonic() + self.request_timeout
                )
                metrics.inc("fastsync_requests_total")
                self.node.network.send_to(
                    peer, wire.trie_nodes_request_id(rid, want)
                )
            if not self._inflight:
                if not len(frontier):
                    break
                await self._sleep_until_backoff(live)
                continue
            await self._wait_wake()
            downloaded += self._drain_replies(frontier, kv)
        frontier.clear()
        metrics.set_gauge("fastsync_frontier_peak", frontier.peak)
        return downloaded

    def _next_batch(self, frontier: BoundedFrontier, kv) -> List[bytes]:
        """Pop up to `batch` MISSING hashes; hashes already present (resume,
        snapshot import, shared subtrees) are walked through inline."""
        want: List[bytes] = []
        while len(want) < self.batch:
            got = frontier.pop_many(self.batch - len(want))
            if not got:
                break
            for h in got:
                if kv.get(prefixed(EntryPrefix.TRIE_NODE, h)) is not None:
                    for c in self._children_of(h):
                        frontier.push(c)
                else:
                    want.append(h)
        return want

    def _pick_peer(self, live: List[bytes], now: float) -> Optional[bytes]:
        candidates = [
            p for p in live if self._score(p).backoff_until <= now
        ]
        if not candidates:
            return None
        counts: Dict[bytes, int] = {}
        for req in self._inflight.values():
            counts[req.peer] = counts.get(req.peer, 0) + 1
        low = min(counts.get(p, 0) for p in candidates)
        pool = [p for p in candidates if counts.get(p, 0) == low]
        self._rr += 1
        return pool[self._rr % len(pool)]

    def _expire_requests(
        self, frontier: BoundedFrontier, now: float
    ) -> None:
        expired = [
            rid
            for rid, req in self._inflight.items()
            if now >= req.deadline or not self._live(req.peer)
        ]
        for rid in expired:
            req = self._inflight.pop(rid)
            if self._live(req.peer):
                self._penalize(req.peer, timeout=True)
            metrics.inc("fastsync_failovers_total")
            frontier.requeue(req.hashes)

    async def _wait_wake(self) -> None:
        now = time.monotonic()
        deadlines = [r.deadline for r in self._inflight.values()]
        delay = max(0.01, min(deadlines) - now) if deadlines else 0.05
        try:
            await asyncio.wait_for(self._wake.wait(), delay)
        except asyncio.TimeoutError:
            pass
        self._wake.clear()

    async def _sleep_until_backoff(self, live: List[bytes]) -> None:
        now = time.monotonic()
        soonest = min(self._score(p).backoff_until for p in live)
        await asyncio.sleep(min(1.0, max(0.01, soonest - now)))

    def _drain_replies(self, frontier: BoundedFrontier, kv) -> int:
        stored = 0
        while self._replies:
            sender, rid, nodes = self._replies.popleft()
            req = self._inflight.get(rid)
            if req is None or req.peer != sender:
                # late, duplicated, or forged reply: the request id makes it
                # unambiguous — drop it, never consume it as another batch
                metrics.inc("fastsync_stale_replies_total")
                continue
            del self._inflight[rid]
            want = set(req.hashes)
            got: Dict[bytes, bytes] = {}
            bad = 0
            for enc in nodes:
                h = keccak256(enc)  # content addressing IS the proof
                if h in want:
                    got[h] = enc
                else:
                    bad += 1
            s = self._score(sender)
            if bad:
                self._ban(sender, bad)
            puts = []
            for h, enc in got.items():
                if kv.get(prefixed(EntryPrefix.TRIE_NODE, h)) is None:
                    puts.append((prefixed(EntryPrefix.TRIE_NODE, h), enc))
            if puts:
                kv.write_batch(puts)
                stored += len(puts)
                # progress counter served by la_getDownloadedNodesTillNow
                metrics.inc("fastsync_nodes_downloaded_total", len(puts))
            if got:
                s.served += len(got)
                metrics.inc(
                    "fastsync_peer_served_total",
                    len(got),
                    labels=_plabel(sender),
                )
            missing = [h for h in req.hashes if h not in got]
            if missing:
                s.misses += len(missing)
                if s.live():
                    self._penalize(sender, timeout=False)
                frontier.requeue(missing)
            elif not bad:
                s.consecutive_failures = 0
                s.backoff_until = 0.0
            for h in got:
                for c in self._children_of(h):
                    frontier.push(c)
        return stored

    def _children_of(self, h: bytes) -> List[bytes]:
        node = self.node.state.trie._load(h)
        if isinstance(node, InternalNode):
            return [c for c in node.children if c != EMPTY_ROOT]
        return []
