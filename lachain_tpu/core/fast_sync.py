"""Fast state sync: trie-node download instead of block replay.

Parity with the reference's fast synchronizer
(/root/reference/src/Lachain.Core/Network/FastSynchronizerBatch.cs:13-50,
StateDownloader.cs:1-316, RequestManager.cs:1-174): a fresh node fetches the
STATE at a recent height directly — here node-by-node from the
content-addressed trie — and only then follows the chain normally.

The content-addressed redesign makes the download TRUSTLESS at the node
level: every received node must hash (keccak256) to the hash that requested
it, so a malicious peer cannot substitute state. Trust roots:

  * the target block's validator multisig is checked against a key set the
    syncing node knows — the genesis set by default, or an operator-supplied
    (height, block_hash) checkpoint when the chain has rotated validators
    (the reference has the same bootstrap assumption: a fresh node cannot
    verify deep rotations without replaying them)
  * the downloaded roots must hash to the block header's state_hash

Flow: pick best peer -> fast_sync_request -> verify block + roots ->
BFS-download missing trie nodes in batches (hash-verified, resumable by
construction: present nodes are skipped) -> commit roots at the target
height -> normal BlockSynchronizer continues from there.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Set, Tuple

from ..crypto.hashes import keccak256
from ..network import wire
from ..storage.kv import EntryPrefix, prefixed
from ..utils import metrics
from ..storage.state import StateRoots
from ..storage.trie import EMPTY_ROOT, InternalNode
from .synchronizer import verify_block_multisig
from .types import Block

logger = logging.getLogger(__name__)

BATCH = 256  # node hashes per request (reference batch download workers)


class FastSynchronizer:
    def __init__(
        self,
        node,
        *,
        trusted: Optional[Tuple[int, bytes]] = None,
        batch: int = BATCH,
    ):
        """`node`: the owning core.node.Node. `trusted`: optional
        (height, block_hash) checkpoint that overrides multisig
        verification for the target block."""
        self.node = node
        self.trusted = trusted
        self.batch = batch
        self._reply: Optional[Tuple[Optional[Block], bytes]] = None
        self._peer: Optional[bytes] = None  # peer of the in-flight sync
        self._nodes_event = asyncio.Event()
        self._reply_event = asyncio.Event()
        self._received: List[bytes] = []
        net = node.network
        net.on_fast_sync_request = self._serve_fast_sync
        net.on_fast_sync_reply = self._on_fast_sync_reply
        net.on_trie_nodes_request = self._serve_trie_nodes
        net.on_trie_nodes_reply = self._on_trie_nodes_reply

    # -- serving side --------------------------------------------------------

    def _serve_fast_sync(self, sender: bytes, height: int) -> None:
        bm = self.node.block_manager
        if height == 0:
            height = bm.current_height()
        block = bm.block_by_height(height)
        roots = self.node.state.roots_at(height)
        if block is None or roots is None:
            self.node.network.send_to(sender, wire.fast_sync_reply(None, b""))
            return
        self.node.network.send_to(
            sender, wire.fast_sync_reply(block, roots.encode())
        )

    def _serve_trie_nodes(self, sender: bytes, hashes: List[bytes]) -> None:
        kv = self.node.kv
        out = []
        for h in hashes[: 4 * self.batch]:
            enc = kv.get(prefixed(EntryPrefix.TRIE_NODE, h))
            if enc is not None:
                out.append(enc)
        self.node.network.send_to(sender, wire.trie_nodes_reply(out))

    # -- client side ---------------------------------------------------------

    def _on_fast_sync_reply(self, sender, block, roots_enc) -> None:
        # only the peer we asked, and only while a request is in flight —
        # any other connected peer could otherwise inject a stale-but-signed
        # snapshot (pinning a fresh node asking for height=0 to old state)
        # or poison the node download into a spurious abort
        if self._peer is None or sender != self._peer or self._reply_event.is_set():
            return
        self._reply = (block, roots_enc)
        self._reply_event.set()

    def _on_trie_nodes_reply(self, sender, nodes: List[bytes]) -> None:
        if self._peer is None or sender != self._peer:
            return
        self._received.extend(nodes)
        self._nodes_event.set()

    async def sync(
        self, peer_pub: bytes, height: int = 0, timeout: float = 60.0
    ) -> int:
        """Download the state at `height` (0 = peer's tip) from `peer_pub`.
        Returns the synced height. Raises on verification failure."""
        node = self.node
        self._reply = None
        self._peer = peer_pub
        self._reply_event.clear()
        try:
            return await self._sync_inner(peer_pub, height, timeout)
        finally:
            self._peer = None  # stop accepting replies once the sync ends

    async def _sync_inner(self, peer_pub: bytes, height: int, timeout: float) -> int:
        node = self.node
        node.network.send_to(peer_pub, wire.fast_sync_request(height))
        await asyncio.wait_for(self._reply_event.wait(), timeout)
        block, roots_enc = self._reply or (None, b"")
        if block is None:
            raise ValueError("peer served no fast-sync snapshot")
        target = block.header.index
        roots = StateRoots.decode(roots_enc)
        if roots.state_hash() != block.header.state_hash:
            raise ValueError("fast-sync roots do not match the block header")
        if self.trusted is not None:
            t_height, t_hash = self.trusted
            if target != t_height or block.hash() != t_hash:
                raise ValueError("fast-sync block differs from checkpoint")
        elif not verify_block_multisig(
            block, node.validator_manager.genesis_keys
        ):
            raise ValueError(
                "fast-sync block lacks a known-validator quorum "
                "(provide a trusted checkpoint for rotated chains)"
            )

        downloaded = await self._download_nodes(peer_pub, roots, timeout)
        # install: state + block + height index (the block itself, so the
        # chain links for subsequent normal sync; tx bodies are not needed)
        bm = node.block_manager
        node.kv.write_batch(
            [
                (
                    prefixed(EntryPrefix.BLOCK_BY_HASH, block.hash()),
                    block.encode(),
                ),
                (
                    prefixed(
                        EntryPrefix.BLOCK_HASH_BY_HEIGHT,
                        wire.write_u64(target),
                    ),
                    block.hash(),
                ),
            ]
        )
        node.state.commit(target, roots)
        logger.info(
            "fast sync complete: height %d, %d trie nodes downloaded",
            target,
            downloaded,
        )
        return target

    async def _download_nodes(
        self, peer_pub: bytes, roots: StateRoots, timeout: float
    ) -> int:
        """BFS over missing nodes, batched; every node hash-verified.
        Naturally resumable: nodes already in the KV are skipped."""
        kv = self.node.kv
        pending: List[bytes] = [
            r for r in roots.all_roots() if r != EMPTY_ROOT
        ]
        seen: Set[bytes] = set(pending)
        downloaded = 0
        while pending:
            want: List[bytes] = []
            rest: List[bytes] = []
            for h in pending:
                if kv.get(prefixed(EntryPrefix.TRIE_NODE, h)) is not None:
                    # already present (resume or shared subtree): still must
                    # walk its children
                    rest.extend(self._children_of(h, seen))
                elif len(want) < self.batch:
                    want.append(h)
                else:
                    rest.append(h)
            if not want:
                pending = rest
                continue
            self._received = []
            self._nodes_event.clear()
            self.node.network.send_to(
                peer_pub, wire.trie_nodes_request(want)
            )
            await asyncio.wait_for(self._nodes_event.wait(), timeout)
            got: Dict[bytes, bytes] = {}
            for enc in self._received:
                got[keccak256(enc)] = enc  # content addressing IS the proof
            missing = [h for h in want if h not in got]
            if missing:
                raise ValueError(
                    f"peer failed to serve {len(missing)} trie nodes"
                )
            puts = []
            for h in want:
                puts.append((prefixed(EntryPrefix.TRIE_NODE, h), got[h]))
            kv.write_batch(puts)
            downloaded += len(want)
            # progress counter served by la_getDownloadedNodesTillNow
            metrics.inc("fastsync_nodes_downloaded", len(want))
            for h in want:
                rest.extend(self._children_of(h, seen))
            pending = rest
        return downloaded

    def _children_of(self, h: bytes, seen: Set[bytes]) -> List[bytes]:
        node = self.node.state.trie._load(h)
        out = []
        if isinstance(node, InternalNode):
            for c in node.children:
                if c != EMPTY_ROOT and c not in seen:
                    seen.add(c)
                    out.append(c)
        return out
