"""System contracts: in-process "precompiles" dispatched by address.

Parity with the reference's system-contract layer
(/root/reference/src/Lachain.Core/Blockchain/SystemContracts/):
  * ContractRegisterer — address 0x0..0x4 dispatch via a selector registry
    (ContractManager/ContractRegisterer.cs:28-62)
  * DeployContract      (DeployContract.cs:1-213)   -> address 0x0
  * NativeTokenContract (NativeTokenContract.cs, LRC-20) -> 0x1
  * GovernanceContract  (GovernanceContract.cs: keygen tx lifecycle +
    ChangeValidators + FinishCycle)                 -> 0x2
  * StakingContract     (StakingContract.cs: stake lifecycle + VRF lottery
    SubmitVrf/FinishVrfLottery + cycle constants)   -> 0x3

ABI: 4-byte keccak selector + fixed-width args (role of ContractEncoder /
ContractDecoder, VM/ContractEncoder.cs:1-169). Contract storage lives in the
'storage' subtree under (contract_address || key).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import vrf
from ..crypto.hashes import keccak256
from ..storage.state import Snapshot
from ..utils.serialization import Reader, write_bytes, write_u32, write_u64, write_u256
from . import execution
from .types import ADDRESS_BYTES, Transaction, ZERO_ADDRESS

DEPLOY_ADDRESS = b"\x00" * 19 + b"\x00"
NATIVE_TOKEN_ADDRESS = b"\x00" * 19 + b"\x01"
GOVERNANCE_ADDRESS = b"\x00" * 19 + b"\x02"
STAKING_ADDRESS = b"\x00" * 19 + b"\x03"

# cycle parameters (reference StakingContract.cs:63-71; config-initialized)
CYCLE_DURATION = 1000  # blocks per validator cycle
VRF_SUBMISSION_PHASE = 500  # blocks of the cycle accepting VRF submissions
ATTENDANCE_DETECTION_DURATION = 100


def set_cycle_params(
    cycle_duration: int,
    vrf_submission_phase: int,
    attendance_detection: int = ATTENDANCE_DETECTION_DURATION,
) -> None:
    """Initialize cycle constants from config (reference
    StakingContract.Initialize, StakingContract.cs:186-197). Must be set
    identically on every node before the chain starts."""
    global CYCLE_DURATION, VRF_SUBMISSION_PHASE, ATTENDANCE_DETECTION_DURATION
    assert 0 < vrf_submission_phase < cycle_duration
    CYCLE_DURATION = cycle_duration
    VRF_SUBMISSION_PHASE = vrf_submission_phase
    ATTENDANCE_DETECTION_DURATION = attendance_detection


def selector(signature: str) -> bytes:
    return keccak256(signature.encode())[:4]


# method selectors
SEL_DEPLOY = selector("deploy(bytes)")
SEL_TRANSFER = selector("transfer(address,uint256)")
SEL_BALANCE_OF = selector("balanceOf(address)")
SEL_TOTAL_SUPPLY = selector("totalSupply()")
SEL_BECOME_STAKER = selector("becomeStaker(bytes,uint256)")
SEL_REQUEST_WITHDRAW = selector("requestStakeWithdrawal(bytes)")
SEL_WITHDRAW = selector("withdrawStake(bytes)")
SEL_SUBMIT_VRF = selector("submitVrf(bytes,bytes)")
SEL_FINISH_LOTTERY = selector("finishVrfLottery()")
SEL_GET_STAKE = selector("getStake(address)")
SEL_KEYGEN_COMMIT = selector("keygenCommit(bytes)")
SEL_KEYGEN_SEND_VALUE = selector("keygenSendValue(uint256,bytes)")
SEL_KEYGEN_CONFIRM = selector("keygenConfirm(bytes)")
SEL_CHANGE_VALIDATORS = selector("changeValidators(bytes)")
SEL_FINISH_CYCLE = selector("finishCycle()")


def _skey(contract: bytes, key: bytes) -> bytes:
    return contract + key


class SystemContractContext:
    """Shared context handed to every contract call."""

    def __init__(self, snap: Snapshot, sender: bytes, tx: Transaction, block: int):
        self.snap = snap
        self.sender = sender
        self.tx = tx
        self.block = block
        self.events: List[Tuple[bytes, bytes]] = []

    # contract-storage accessors ('storage' subtree)
    def sget(self, contract: bytes, key: bytes) -> Optional[bytes]:
        return self.snap.get("storage", _skey(contract, key))

    def sput(self, contract: bytes, key: bytes, value: bytes) -> None:
        self.snap.put("storage", _skey(contract, key), value)

    def sdel(self, contract: bytes, key: bytes) -> None:
        self.snap.delete("storage", _skey(contract, key))

    def emit(self, contract: bytes, data: bytes) -> None:
        self.events.append((contract, data))
        self.snap.put(
            "events",
            keccak256(contract + data + write_u64(self.block)),
            contract + data,
        )


# ---------------------------------------------------------------------------
# Deploy (reference DeployContract.cs) — stores contract bytecode; execution
# of deployed code arrives with the VM layer.
# ---------------------------------------------------------------------------


def deploy_contract(ctx: SystemContractContext, args: Reader) -> Tuple[int, bytes]:
    from ..vm.vm import deploy_code

    code = args.bytes_()
    if not code or len(code) > 512 * 1024:
        return 0, b""
    status, addr = deploy_code(ctx.snap, ctx.sender, ctx.tx.nonce, code)
    if status != 1:
        return 0, b""
    ctx.emit(DEPLOY_ADDRESS, b"deployed" + addr)
    return 1, addr


# ---------------------------------------------------------------------------
# Native token (reference NativeTokenContract.cs, LRC-20 surface)
# ---------------------------------------------------------------------------


def native_token(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_TOTAL_SUPPLY:
        # supply = sum of genesis allocations + staking rewards; tracked key
        raw = ctx.sget(NATIVE_TOKEN_ADDRESS, b"supply")
        return 1, raw or write_u256(0)
    if sel == SEL_BALANCE_OF:
        addr = args.raw(ADDRESS_BYTES)
        return 1, write_u256(execution.get_balance(ctx.snap, addr))
    if sel == SEL_TRANSFER:
        to = args.raw(ADDRESS_BYTES)
        amount = args.u256()
        bal = execution.get_balance(ctx.snap, ctx.sender)
        if bal < amount:
            return 0, b""
        execution.set_balance(ctx.snap, ctx.sender, bal - amount)
        execution.set_balance(
            ctx.snap, to, execution.get_balance(ctx.snap, to) + amount
        )
        ctx.emit(NATIVE_TOKEN_ADDRESS, b"transfer" + ctx.sender + to + write_u256(amount))
        return 1, write_u256(1)
    return 0, b""


# ---------------------------------------------------------------------------
# Staking (reference StakingContract.cs): stake lifecycle + VRF lottery
# ---------------------------------------------------------------------------


def _stakers_key() -> bytes:
    return b"stakers"


def _get_staker_list(ctx) -> List[bytes]:
    raw = ctx.sget(STAKING_ADDRESS, _stakers_key())
    if not raw:
        return []
    r = Reader(raw)
    return r.bytes_list()


def _put_staker_list(ctx, stakers: List[bytes]) -> None:
    from ..utils.serialization import write_bytes_list

    ctx.sput(STAKING_ADDRESS, _stakers_key(), write_bytes_list(stakers))


def staking(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_BECOME_STAKER:
        pubkey = args.bytes_()  # validator ECDSA pubkey
        amount = args.u256()
        if len(pubkey) != 33 or amount <= 0:
            return 0, b""
        bal = execution.get_balance(ctx.snap, ctx.sender)
        if bal < amount:
            return 0, b""
        execution.set_balance(ctx.snap, ctx.sender, bal - amount)
        prev = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        prev_amount = int.from_bytes(prev, "big") if prev else 0
        ctx.sput(
            STAKING_ADDRESS, b"stake:" + ctx.sender, write_u256(prev_amount + amount)
        )
        ctx.sput(STAKING_ADDRESS, b"pub:" + ctx.sender, pubkey)
        stakers = _get_staker_list(ctx)
        if ctx.sender not in stakers:
            stakers.append(ctx.sender)
            _put_staker_list(ctx, stakers)
        total = ctx.sget(STAKING_ADDRESS, b"total")
        total_amount = int.from_bytes(total, "big") if total else 0
        ctx.sput(STAKING_ADDRESS, b"total", write_u256(total_amount + amount))
        ctx.emit(STAKING_ADDRESS, b"staked" + ctx.sender + write_u256(amount))
        return 1, b""

    if sel == SEL_GET_STAKE:
        addr = args.raw(ADDRESS_BYTES)
        raw = ctx.sget(STAKING_ADDRESS, b"stake:" + addr)
        return 1, raw or write_u256(0)

    if sel == SEL_REQUEST_WITHDRAW:
        # withdrawal queued; paid out at the cycle boundary (reference's
        # two-phase withdrawal, StakingContract withdrawal flow)
        raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        if not raw or int.from_bytes(raw, "big") == 0:
            return 0, b""
        ctx.sput(STAKING_ADDRESS, b"withdraw:" + ctx.sender, raw)
        return 1, b""

    if sel == SEL_WITHDRAW:
        raw = ctx.sget(STAKING_ADDRESS, b"withdraw:" + ctx.sender)
        if not raw:
            return 0, b""
        amount = int.from_bytes(raw, "big")
        stake_raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        stake_amount = int.from_bytes(stake_raw, "big") if stake_raw else 0
        pay = min(amount, stake_amount)
        if pay == 0:
            return 0, b""
        ctx.sput(STAKING_ADDRESS, b"stake:" + ctx.sender, write_u256(stake_amount - pay))
        ctx.sdel(STAKING_ADDRESS, b"withdraw:" + ctx.sender)
        total = int.from_bytes(ctx.sget(STAKING_ADDRESS, b"total") or b"", "big") if ctx.sget(STAKING_ADDRESS, b"total") else 0
        ctx.sput(STAKING_ADDRESS, b"total", write_u256(max(total - pay, 0)))
        execution.set_balance(
            ctx.snap,
            ctx.sender,
            execution.get_balance(ctx.snap, ctx.sender) + pay,
        )
        ctx.emit(STAKING_ADDRESS, b"withdrawn" + ctx.sender + write_u256(pay))
        return 1, b""

    if sel == SEL_SUBMIT_VRF:
        # (reference SubmitVrf, StakingContract.cs:458-537): within the VRF
        # phase, a staker proves a winning lottery roll for the next cycle
        if ctx.block % CYCLE_DURATION >= VRF_SUBMISSION_PHASE:
            return 0, b""
        pubkey = args.bytes_()
        proof = args.bytes_()
        stored_pub = ctx.sget(STAKING_ADDRESS, b"pub:" + ctx.sender)
        if stored_pub != pubkey:
            return 0, b""
        stake_raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        stake_amount = int.from_bytes(stake_raw, "big") if stake_raw else 0
        if stake_amount == 0:
            return 0, b""
        total_raw = ctx.sget(STAKING_ADDRESS, b"total")
        total = int.from_bytes(total_raw, "big") if total_raw else 0
        cycle = ctx.block // CYCLE_DURATION
        seed = ctx.sget(STAKING_ADDRESS, b"seed") or b"genesis-seed"
        alpha = seed + write_u64(cycle)
        if not vrf.verify(pubkey, alpha, proof):
            return 0, b""
        beta = vrf.proof_to_hash(proof)
        expected = int.from_bytes(
            ctx.sget(STAKING_ADDRESS, b"validators_count") or write_u32(7), "big"
        )
        if not vrf.is_winner(beta, stake_amount, total, expected):
            return 0, b""
        # record the winner for the cycle
        key = b"winner:" + write_u64(cycle) + ctx.sender
        if ctx.sget(STAKING_ADDRESS, key) is not None:
            return 0, b""  # duplicate submission
        ctx.sput(STAKING_ADDRESS, key, pubkey + beta)
        winners = _get_winner_list(ctx, cycle)
        winners.append(ctx.sender)
        _put_winner_list(ctx, cycle, winners)
        ctx.emit(STAKING_ADDRESS, b"vrf" + ctx.sender)
        return 1, b""

    if sel == SEL_FINISH_LOTTERY:
        # (reference FinishVrfLottery, StakingContract.cs:738-747): close the
        # phase, pick the next validator set from the winners. Only valid
        # once per cycle, after the submission phase has ended — otherwise
        # anyone could reroll the seed mid-phase and grind the election.
        cycle = ctx.block // CYCLE_DURATION
        if ctx.block % CYCLE_DURATION < VRF_SUBMISSION_PHASE:
            return 0, b""
        if ctx.sget(STAKING_ADDRESS, b"lottery_done:" + write_u64(cycle)):
            return 0, b""
        winners = _get_winner_list(ctx, cycle)
        pubs = []
        for w in winners:
            rec = ctx.sget(STAKING_ADDRESS, b"winner:" + write_u64(cycle) + w)
            if rec:
                pubs.append(rec[:33])
        if pubs:
            from ..utils.serialization import write_bytes_list

            ctx.sput(STAKING_ADDRESS, b"lottery_done:" + write_u64(cycle), b"\x01")
            ctx.sput(
                STAKING_ADDRESS,
                b"next_validators",
                write_bytes_list(pubs),
            )
            # roll the seed forward
            ctx.sput(
                STAKING_ADDRESS,
                b"seed",
                keccak256((ctx.sget(STAKING_ADDRESS, b"seed") or b"") + write_u64(cycle)),
            )
            ctx.emit(STAKING_ADDRESS, b"lottery_done" + write_u64(cycle))
            return 1, b""
        return 0, b""

    return 0, b""


def _get_winner_list(ctx, cycle: int) -> List[bytes]:
    raw = ctx.sget(STAKING_ADDRESS, b"winners:" + write_u64(cycle))
    if not raw:
        return []
    return Reader(raw).bytes_list()


def _put_winner_list(ctx, cycle: int, winners: List[bytes]) -> None:
    from ..utils.serialization import write_bytes_list

    ctx.sput(
        STAKING_ADDRESS, b"winners:" + write_u64(cycle), write_bytes_list(winners)
    )


# ---------------------------------------------------------------------------
# Governance (reference GovernanceContract.cs): keygen tx lifecycle + the
# validator-set change. The DKG math itself lives in consensus/keygen.py;
# these methods are the on-chain message board the keygen rides on.
# ---------------------------------------------------------------------------


def _is_next_validator(ctx: SystemContractContext) -> bool:
    """Sender gating for the keygen message board: only addresses of the
    LOTTERY-ELECTED set may post (reference GovernanceContract keygen
    methods check the sender against the cycle's validator set,
    GovernanceContract.cs:117-217). Without this, any funded address could
    sybil n-f confirms and install an attacker validator set."""
    from ..crypto import ecdsa as _ecdsa

    nv_raw = ctx.sget(STAKING_ADDRESS, b"next_validators")
    if not nv_raw:
        return False
    for pub in Reader(nv_raw).bytes_list():
        if _ecdsa.address_from_public_key(pub) == ctx.sender:
            return True
    return False


def governance(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_KEYGEN_COMMIT:
        if not _is_next_validator(ctx):
            return 0, b""
        blob = args.bytes_()
        key = b"commit:" + write_u64(ctx.block // CYCLE_DURATION) + ctx.sender
        ctx.sput(GOVERNANCE_ADDRESS, key, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_commit" + ctx.sender + blob)
        return 1, b""
    if sel == SEL_KEYGEN_SEND_VALUE:
        if not _is_next_validator(ctx):
            return 0, b""
        round_no = args.u256()
        blob = args.bytes_()
        key = (
            b"value:"
            + write_u64(ctx.block // CYCLE_DURATION)
            + write_u64(round_no & 0xFFFFFFFFFFFFFFFF)
            + ctx.sender
        )
        ctx.sput(GOVERNANCE_ADDRESS, key, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_value" + ctx.sender + blob)
        return 1, b""
    if sel == SEL_KEYGEN_CONFIRM:
        if not _is_next_validator(ctx):
            return 0, b""
        blob = args.bytes_()  # serialized new public key set
        cycle = ctx.block // CYCLE_DURATION
        h = keccak256(blob)
        cnt_key = b"confirms:" + write_u64(cycle) + h
        raw = ctx.sget(GOVERNANCE_ADDRESS, cnt_key)
        voters = Reader(raw).bytes_list() if raw else []
        if ctx.sender in voters:
            return 0, b""
        voters.append(ctx.sender)
        from ..utils.serialization import write_bytes_list

        ctx.sput(GOVERNANCE_ADDRESS, cnt_key, write_bytes_list(voters))
        ctx.sput(GOVERNANCE_ADDRESS, b"candidate:" + h, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_confirm" + ctx.sender)
        # N-F matching confirms from the elected set finalize the rotation
        # (reference GovernanceContract.Confirm -> ChangeValidators,
        # GovernanceContract.cs:283-331)
        nv_raw = ctx.sget(STAKING_ADDRESS, b"next_validators")
        if nv_raw:
            n_next = len(Reader(nv_raw).bytes_list())
            f_next = (n_next - 1) // 3
            if len(voters) >= n_next - f_next:
                ctx.sput(GOVERNANCE_ADDRESS, b"pending_validators", blob)
                ctx.emit(GOVERNANCE_ADDRESS, b"validators_changed" + h)
        return 1, write_u32(len(voters))
    if sel == SEL_CHANGE_VALIDATORS:
        # In the reference this is an internal transition invoked by the
        # confirm threshold (GovernanceContract.cs:283-331), never a public
        # entry point; exposing it lets one funded address install an
        # arbitrary validator set. The only path to pending_validators is
        # the n-f keygen-confirm quorum above.
        return 0, b""
    if sel == SEL_FINISH_CYCLE:
        # only the cycle's LAST block may rotate the set: the new keys are
        # wallet-installed from era (cycle+1)*CYCLE_DURATION, so the
        # validator-set flip must land in the snapshot of exactly the block
        # before (reference injects FinishCycle as a cycle-boundary system
        # tx, BlockProducer.cs:126-146)
        if ctx.block % CYCLE_DURATION != CYCLE_DURATION - 1:
            return 0, b""
        pending = ctx.sget(GOVERNANCE_ADDRESS, b"pending_validators")
        if pending:
            ctx.snap.put("validators", b"current", pending)
            ctx.sdel(GOVERNANCE_ADDRESS, b"pending_validators")
            ctx.emit(GOVERNANCE_ADDRESS, b"cycle_finished")
            return 1, b""
        return 0, b""
    return 0, b""


# ---------------------------------------------------------------------------
# Registry / dispatcher (reference ContractRegisterer.cs)
# ---------------------------------------------------------------------------


def dispatch(
    snap: Snapshot,
    sender: bytes,
    tx: Transaction,
    block: int,
    tx_hash: Optional[bytes] = None,
) -> Tuple[int, bytes]:
    ctx = SystemContractContext(snap, sender, tx, block)
    data = tx.invocation
    if len(data) < 4:
        return 0, b""
    sel, rest = data[:4], Reader(data[4:])
    try:
        if tx.to == DEPLOY_ADDRESS and sel == SEL_DEPLOY:
            result = deploy_contract(ctx, rest)
        elif tx.to == NATIVE_TOKEN_ADDRESS:
            result = native_token(ctx, sel, rest)
        elif tx.to == STAKING_ADDRESS:
            result = staking(ctx, sel, rest)
        elif tx.to == GOVERNANCE_ADDRESS:
            result = governance(ctx, sel, rest)
        else:
            return 0, b""
    except (ValueError, AssertionError):
        return 0, b""
    # persist emitted events so node services (KeyGenManager) can react to
    # executed system txs (reference: BlockManager.OnSystemContractInvoked,
    # BlockManager.cs:171-176, 547-560)
    if result[0] == 1 and tx_hash is not None:
        from ..utils.serialization import write_u32 as _u32

        for i, (contract, payload) in enumerate(ctx.events):
            snap.put("events", tx_hash + _u32(i), contract + payload)
    return result


SYSTEM_CONTRACTS: Dict[bytes, Callable] = {
    addr: dispatch
    for addr in (
        DEPLOY_ADDRESS,
        NATIVE_TOKEN_ADDRESS,
        GOVERNANCE_ADDRESS,
        STAKING_ADDRESS,
    )
}


def make_executer(chain_id: int) -> execution.TransactionExecuter:
    """TransactionExecuter wired with the system-contract registry."""
    return execution.TransactionExecuter(
        chain_id,
        system_contracts=dict(SYSTEM_CONTRACTS),
    )
