"""System contracts: in-process "precompiles" dispatched by address.

Parity with the reference's system-contract layer
(/root/reference/src/Lachain.Core/Blockchain/SystemContracts/):
  * ContractRegisterer — address 0x0..0x4 dispatch via a selector registry
    (ContractManager/ContractRegisterer.cs:28-62)
  * DeployContract      (DeployContract.cs:1-213)   -> address 0x0
  * NativeTokenContract (NativeTokenContract.cs, LRC-20) -> 0x1
  * GovernanceContract  (GovernanceContract.cs: keygen tx lifecycle +
    ChangeValidators + FinishCycle)                 -> 0x2
  * StakingContract     (StakingContract.cs: stake lifecycle + VRF lottery
    SubmitVrf/FinishVrfLottery + cycle constants)   -> 0x3

ABI: 4-byte keccak selector + fixed-width args (role of ContractEncoder /
ContractDecoder, VM/ContractEncoder.cs:1-169). Contract storage lives in the
'storage' subtree under (contract_address || key).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import vrf
from ..crypto.hashes import keccak256
from ..storage.state import Snapshot
from ..utils.serialization import Reader, write_u32, write_u64, write_u256
from . import execution
from .types import ADDRESS_BYTES, Transaction

DEPLOY_ADDRESS = b"\x00" * 19 + b"\x00"
NATIVE_TOKEN_ADDRESS = b"\x00" * 19 + b"\x01"
GOVERNANCE_ADDRESS = b"\x00" * 19 + b"\x02"
STAKING_ADDRESS = b"\x00" * 19 + b"\x03"

# cycle parameters (reference StakingContract.cs:63-71; config-initialized)
CYCLE_DURATION = 1000  # blocks per validator cycle
VRF_SUBMISSION_PHASE = 500  # blocks of the cycle accepting VRF submissions
ATTENDANCE_DETECTION_DURATION = 100
# per-cycle reward pool distributed by attendance (reference
# DistributeRewardsAndPenalties' totalReward; a validator that skips the
# detection check-in forfeits its share AND accrues that much penalty
# against its stake, StakingContract.cs:656-720)
ATTENDANCE_CYCLE_REWARD = 1000 * 10**18
# how many cycles back a finish tx will lazily settle orphaned attendance
# state (a cycle whose close tx never landed); bounds per-tx work
ATTENDANCE_SETTLE_LOOKBACK = 8


def set_cycle_params(
    cycle_duration: int,
    vrf_submission_phase: int,
    attendance_detection: int = ATTENDANCE_DETECTION_DURATION,
) -> None:
    """Initialize cycle constants from config (reference
    StakingContract.Initialize, StakingContract.cs:186-197). Must be set
    identically on every node before the chain starts."""
    global CYCLE_DURATION, VRF_SUBMISSION_PHASE, ATTENDANCE_DETECTION_DURATION
    assert 0 < vrf_submission_phase < cycle_duration
    # the detection window must CLOSE within the cycle or finish/settlement
    # can never run; clamp deterministically (same config -> same params on
    # every node) rather than brick short-cycle configs
    attendance_detection = max(1, min(attendance_detection, cycle_duration - 1))
    CYCLE_DURATION = cycle_duration
    VRF_SUBMISSION_PHASE = vrf_submission_phase
    ATTENDANCE_DETECTION_DURATION = attendance_detection


def selector(signature: str) -> bytes:
    return keccak256(signature.encode())[:4]


# method selectors
SEL_DEPLOY = selector("deploy(bytes)")
SEL_TRANSFER = selector("transfer(address,uint256)")
SEL_BALANCE_OF = selector("balanceOf(address)")
SEL_TOTAL_SUPPLY = selector("totalSupply()")
SEL_BECOME_STAKER = selector("becomeStaker(bytes,uint256)")
SEL_REQUEST_WITHDRAW = selector("requestStakeWithdrawal(bytes)")
SEL_WITHDRAW = selector("withdrawStake(bytes)")
SEL_SUBMIT_VRF = selector("submitVrf(bytes,bytes)")
SEL_FINISH_LOTTERY = selector("finishVrfLottery()")
SEL_GET_STAKE = selector("getStake(address)")
SEL_KEYGEN_COMMIT = selector("keygenCommit(bytes)")
SEL_KEYGEN_SEND_VALUE = selector("keygenSendValue(uint256,bytes)")
SEL_KEYGEN_CONFIRM = selector("keygenConfirm(bytes)")
SEL_CHANGE_VALIDATORS = selector("changeValidators(bytes)")
SEL_FINISH_CYCLE = selector("finishCycle()")
SEL_SUBMIT_ATTENDANCE = selector("submitAttendanceDetection(bytes[],uint256[])")
SEL_FINISH_ATTENDANCE = selector("finishAttendanceDetection()")
SEL_GET_PENALTY = selector("getPenalty(address)")


def _skey(contract: bytes, key: bytes) -> bytes:
    return contract + key


class SystemContractContext:
    """Shared context handed to every contract call."""

    def __init__(self, snap: Snapshot, sender: bytes, tx: Transaction, block: int):
        self.snap = snap
        self.sender = sender
        self.tx = tx
        self.block = block
        self.events: List[Tuple[bytes, bytes]] = []

    # contract-storage accessors ('storage' subtree)
    def sget(self, contract: bytes, key: bytes) -> Optional[bytes]:
        return self.snap.get("storage", _skey(contract, key))

    def sput(self, contract: bytes, key: bytes, value: bytes) -> None:
        self.snap.put("storage", _skey(contract, key), value)

    def sdel(self, contract: bytes, key: bytes) -> None:
        self.snap.delete("storage", _skey(contract, key))

    def emit(self, contract: bytes, data: bytes) -> None:
        self.events.append((contract, data))
        self.snap.put(
            "events",
            keccak256(contract + data + write_u64(self.block)),
            contract + data,
        )


# ---------------------------------------------------------------------------
# Deploy (reference DeployContract.cs) — stores contract bytecode; execution
# of deployed code arrives with the VM layer.
# ---------------------------------------------------------------------------


def deploy_contract(ctx: SystemContractContext, args: Reader) -> Tuple[int, bytes]:
    from ..vm.vm import deploy_code

    code = args.bytes_()
    if not code or len(code) > 512 * 1024:
        return 0, b""
    status, addr = deploy_code(ctx.snap, ctx.sender, ctx.tx.nonce, code)
    if status != 1:
        return 0, b""
    ctx.emit(DEPLOY_ADDRESS, b"deployed" + addr)
    return 1, addr


# ---------------------------------------------------------------------------
# Native token (reference NativeTokenContract.cs, LRC-20 surface)
# ---------------------------------------------------------------------------


def native_token(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_TOTAL_SUPPLY:
        # supply = sum of genesis allocations + staking rewards; tracked key
        raw = ctx.sget(NATIVE_TOKEN_ADDRESS, b"supply")
        return 1, raw or write_u256(0)
    if sel == SEL_BALANCE_OF:
        addr = args.raw(ADDRESS_BYTES)
        return 1, write_u256(execution.get_balance(ctx.snap, addr))
    if sel == SEL_TRANSFER:
        to = args.raw(ADDRESS_BYTES)
        amount = args.u256()
        bal = execution.get_balance(ctx.snap, ctx.sender)
        if bal < amount:
            return 0, b""
        execution.set_balance(ctx.snap, ctx.sender, bal - amount)
        execution.set_balance(
            ctx.snap, to, execution.get_balance(ctx.snap, to) + amount
        )
        ctx.emit(NATIVE_TOKEN_ADDRESS, b"transfer" + ctx.sender + to + write_u256(amount))
        return 1, write_u256(1)
    return 0, b""


# ---------------------------------------------------------------------------
# Staking (reference StakingContract.cs): stake lifecycle + VRF lottery
# ---------------------------------------------------------------------------


def _stakers_key() -> bytes:
    return b"stakers"


def _get_staker_list(ctx) -> List[bytes]:
    raw = ctx.sget(STAKING_ADDRESS, _stakers_key())
    if not raw:
        return []
    r = Reader(raw)
    return r.bytes_list()


def _put_staker_list(ctx, stakers: List[bytes]) -> None:
    from ..utils.serialization import write_bytes_list

    ctx.sput(STAKING_ADDRESS, _stakers_key(), write_bytes_list(stakers))


def staking(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_BECOME_STAKER:
        pubkey = args.bytes_()  # validator ECDSA pubkey
        amount = args.u256()
        if len(pubkey) != 33 or amount <= 0:
            return 0, b""
        bal = execution.get_balance(ctx.snap, ctx.sender)
        if bal < amount:
            return 0, b""
        execution.set_balance(ctx.snap, ctx.sender, bal - amount)
        prev = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        prev_amount = int.from_bytes(prev, "big") if prev else 0
        ctx.sput(
            STAKING_ADDRESS, b"stake:" + ctx.sender, write_u256(prev_amount + amount)
        )
        ctx.sput(STAKING_ADDRESS, b"pub:" + ctx.sender, pubkey)
        stakers = _get_staker_list(ctx)
        if ctx.sender not in stakers:
            stakers.append(ctx.sender)
            _put_staker_list(ctx, stakers)
        total = ctx.sget(STAKING_ADDRESS, b"total")
        total_amount = int.from_bytes(total, "big") if total else 0
        ctx.sput(STAKING_ADDRESS, b"total", write_u256(total_amount + amount))
        ctx.emit(STAKING_ADDRESS, b"staked" + ctx.sender + write_u256(amount))
        return 1, b""

    if sel == SEL_GET_STAKE:
        addr = args.raw(ADDRESS_BYTES)
        raw = ctx.sget(STAKING_ADDRESS, b"stake:" + addr)
        return 1, raw or write_u256(0)

    if sel == SEL_REQUEST_WITHDRAW:
        # withdrawal queued; paid out at the cycle boundary (reference's
        # two-phase withdrawal, StakingContract withdrawal flow)
        raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        if not raw or int.from_bytes(raw, "big") == 0:
            return 0, b""
        ctx.sput(STAKING_ADDRESS, b"withdraw:" + ctx.sender, raw)
        return 1, b""

    if sel == SEL_WITHDRAW:
        raw = ctx.sget(STAKING_ADDRESS, b"withdraw:" + ctx.sender)
        if not raw:
            return 0, b""
        amount = int.from_bytes(raw, "big")
        stake_raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        stake_amount = int.from_bytes(stake_raw, "big") if stake_raw else 0
        pay = min(amount, stake_amount)
        if pay == 0:
            return 0, b""
        # accrued attendance penalties burn out of the unstaked amount
        # first (reference deducts _stakedAddressToPenalty from the
        # withdrawal, StakingContract.cs:396-448): the full `pay` leaves the
        # stake, only `credit` reaches the balance, `burn` is destroyed
        pen_key = b"penalty:" + ctx.sender
        penalty = int.from_bytes(ctx.sget(STAKING_ADDRESS, pen_key) or b"", "big")
        burn = min(penalty, pay)
        credit = pay - burn
        if burn:
            penalty -= burn
            if penalty:
                ctx.sput(STAKING_ADDRESS, pen_key, write_u256(penalty))
            else:
                ctx.sdel(STAKING_ADDRESS, pen_key)
            ctx.emit(
                STAKING_ADDRESS, b"penalty_burned" + ctx.sender + write_u256(burn)
            )
        ctx.sput(STAKING_ADDRESS, b"stake:" + ctx.sender, write_u256(stake_amount - pay))
        ctx.sdel(STAKING_ADDRESS, b"withdraw:" + ctx.sender)
        total = int.from_bytes(ctx.sget(STAKING_ADDRESS, b"total") or b"", "big") if ctx.sget(STAKING_ADDRESS, b"total") else 0
        ctx.sput(STAKING_ADDRESS, b"total", write_u256(max(total - pay, 0)))
        execution.set_balance(
            ctx.snap,
            ctx.sender,
            execution.get_balance(ctx.snap, ctx.sender) + credit,
        )
        ctx.emit(STAKING_ADDRESS, b"withdrawn" + ctx.sender + write_u256(credit))
        return 1, b""

    if sel == SEL_SUBMIT_ATTENDANCE:
        # (reference SubmitAttendanceDetection, StakingContract.cs:538-634):
        # during the first ATTENDANCE_DETECTION_DURATION blocks of a cycle,
        # each previous-cycle validator reports how many blocks it saw every
        # previous-cycle validator co-sign. Reports are votes; the median is
        # taken at finishAttendanceDetection. Checking in at all is what
        # shields a validator from the no-show penalty.
        cycle = ctx.block // CYCLE_DURATION
        if cycle == 0 or ctx.block % CYCLE_DURATION >= ATTENDANCE_DETECTION_DURATION:
            return 0, b""
        entries = args.bytes_list()
        prev_raw = ctx.sget(STAKING_ADDRESS, b"prev_pubs")
        prev_pubs = Reader(prev_raw).bytes_list() if prev_raw else []
        sender_pub = ctx.sget(STAKING_ADDRESS, b"pub:" + ctx.sender)
        if not sender_pub or sender_pub not in prev_pubs:
            return 0, b""
        checkin_key = b"att_checkin:" + write_u64(cycle)
        raw = ctx.sget(STAKING_ADDRESS, checkin_key)
        voters = Reader(raw).bytes_list() if raw else []
        if sender_pub in voters:
            return 0, b""
        # validate the whole report before accepting any of it; duplicate
        # targets are rejected — one voter gets ONE vote per validator, or
        # a single report could stuff the median
        parsed = []
        seen: set = set()
        for e in entries:
            if len(e) != 33 + 4:
                return 0, b""
            pub, cnt = e[:33], int.from_bytes(e[33:], "big")
            if pub not in prev_pubs or pub in seen or cnt > CYCLE_DURATION:
                return 0, b""
            seen.add(pub)
            parsed.append((pub, cnt))
        voters.append(sender_pub)
        from ..utils.serialization import write_bytes_list

        ctx.sput(STAKING_ADDRESS, checkin_key, write_bytes_list(voters))
        for pub, cnt in parsed:
            vkey = b"att_votes:" + write_u64(cycle) + pub
            ctx.sput(
                STAKING_ADDRESS,
                vkey,
                (ctx.sget(STAKING_ADDRESS, vkey) or b"") + write_u32(cnt),
            )
        ctx.emit(STAKING_ADDRESS, b"attendance_submitted" + sender_pub)
        return 1, b""

    if sel == SEL_FINISH_ATTENDANCE:
        # (reference DistributeRewardsAndPenalties, StakingContract.cs:
        # 656-720): once the detection window closes, each previous-cycle
        # validator's reward share scales with the MEDIAN voted block count;
        # a validator that never checked in forfeits its share and accrues
        # it as a penalty against its stake. Idempotent per cycle; any
        # validator may send the close tx once the window has passed (the
        # reference injects it as a block-production system tx instead).
        # A cycle whose close tx never landed before the cycle ended is
        # settled LAZILY here: any later finish first sweeps unsettled
        # prior cycles (their electorate snapshotted at rotation, see
        # SEL_FINISH_CYCLE) so rewards/penalties are never silently lost.
        cycle = ctx.block // CYCLE_DURATION
        if cycle == 0 or ctx.block % CYCLE_DURATION < ATTENDANCE_DETECTION_DURATION:
            return 0, b""
        # `att_settled` is the high-water mark of settled cycles: any cycle
        # above it is unsettled EVEN IF it left no state behind (a fully
        # stalled cycle with zero check-ins must still hand out no-show
        # penalties). Chains predating the watermark fall back to the
        # evidence gate for the one-time transition, since their settled
        # cycles cleaned up their done flags.
        wm_raw = ctx.sget(STAKING_ADDRESS, b"att_settled")
        watermark = int.from_bytes(wm_raw, "big") if wm_raw else None
        settled = 0
        high = watermark or 0
        lo = max(1, cycle - ATTENDANCE_SETTLE_LOOKBACK + 1)
        for x in range(lo, cycle):
            if ctx.sget(STAKING_ADDRESS, b"att_done:" + write_u64(x)):
                continue
            if watermark is not None:
                if x <= watermark:
                    continue
            elif not (
                ctx.sget(STAKING_ADDRESS, b"att_checkin:" + write_u64(x))
                or ctx.sget(STAKING_ADDRESS, b"att_pubs:" + write_u64(x))
            ):
                continue
            if _settle_attendance_cycle(ctx, x):
                settled += 1
                high = max(high, x)
        if not ctx.sget(STAKING_ADDRESS, b"att_done:" + write_u64(cycle)):
            if _settle_attendance_cycle(ctx, cycle):
                settled += 1
                high = cycle
        if settled and high > (watermark or 0):
            ctx.sput(STAKING_ADDRESS, b"att_settled", write_u64(high))
        return (1, b"") if settled else (0, b"")

    if sel == SEL_GET_PENALTY:
        addr = args.raw(ADDRESS_BYTES)
        raw = ctx.sget(STAKING_ADDRESS, b"penalty:" + addr)
        return 1, raw or write_u256(0)

    if sel == SEL_SUBMIT_VRF:
        # (reference SubmitVrf, StakingContract.cs:458-537): within the VRF
        # phase, a staker proves a winning lottery roll for the next cycle
        if ctx.block % CYCLE_DURATION >= VRF_SUBMISSION_PHASE:
            return 0, b""
        pubkey = args.bytes_()
        proof = args.bytes_()
        stored_pub = ctx.sget(STAKING_ADDRESS, b"pub:" + ctx.sender)
        if stored_pub != pubkey:
            return 0, b""
        stake_raw = ctx.sget(STAKING_ADDRESS, b"stake:" + ctx.sender)
        stake_amount = int.from_bytes(stake_raw, "big") if stake_raw else 0
        if stake_amount == 0:
            return 0, b""
        total_raw = ctx.sget(STAKING_ADDRESS, b"total")
        total = int.from_bytes(total_raw, "big") if total_raw else 0
        cycle = ctx.block // CYCLE_DURATION
        seed = ctx.sget(STAKING_ADDRESS, b"seed") or b"genesis-seed"
        alpha = seed + write_u64(cycle)
        if not vrf.verify(pubkey, alpha, proof):
            return 0, b""
        beta = vrf.proof_to_hash(proof)
        expected = int.from_bytes(
            ctx.sget(STAKING_ADDRESS, b"validators_count") or write_u32(7), "big"
        )
        if not vrf.is_winner(beta, stake_amount, total, expected):
            return 0, b""
        # record the winner for the cycle
        key = b"winner:" + write_u64(cycle) + ctx.sender
        if ctx.sget(STAKING_ADDRESS, key) is not None:
            return 0, b""  # duplicate submission
        ctx.sput(STAKING_ADDRESS, key, pubkey + beta)
        winners = _get_winner_list(ctx, cycle)
        winners.append(ctx.sender)
        _put_winner_list(ctx, cycle, winners)
        ctx.emit(STAKING_ADDRESS, b"vrf" + ctx.sender)
        return 1, b""

    if sel == SEL_FINISH_LOTTERY:
        # (reference FinishVrfLottery, StakingContract.cs:738-747): close the
        # phase, pick the next validator set from the winners. Only valid
        # once per cycle, after the submission phase has ended — otherwise
        # anyone could reroll the seed mid-phase and grind the election.
        cycle = ctx.block // CYCLE_DURATION
        if ctx.block % CYCLE_DURATION < VRF_SUBMISSION_PHASE:
            return 0, b""
        if ctx.sget(STAKING_ADDRESS, b"lottery_done:" + write_u64(cycle)):
            return 0, b""
        winners = _get_winner_list(ctx, cycle)
        pubs = []
        for w in winners:
            rec = ctx.sget(STAKING_ADDRESS, b"winner:" + write_u64(cycle) + w)
            if rec:
                pubs.append(rec[:33])
        if pubs:
            from ..utils.serialization import write_bytes_list

            ctx.sput(STAKING_ADDRESS, b"lottery_done:" + write_u64(cycle), b"\x01")
            ctx.sput(
                STAKING_ADDRESS,
                b"next_validators",
                write_bytes_list(pubs),
            )
            # roll the seed forward
            ctx.sput(
                STAKING_ADDRESS,
                b"seed",
                keccak256((ctx.sget(STAKING_ADDRESS, b"seed") or b"") + write_u64(cycle)),
            )
            ctx.emit(STAKING_ADDRESS, b"lottery_done" + write_u64(cycle))
            return 1, b""
        return 0, b""

    return 0, b""


def _settle_attendance_cycle(ctx: SystemContractContext, x: int) -> int:
    """Distribute cycle `x`'s attendance rewards/penalties (reference
    DistributeRewardsAndPenalties, StakingContract.cs:656-720) and clean its
    per-cycle state. Electorate: the rotation-time snapshot `att_pubs:x` if
    the validator set changed since, else the live prev_pubs. Returns 1 if
    settled, 0 if there was no electorate to settle against."""
    cyc = write_u64(x)
    pubs_raw = ctx.sget(STAKING_ADDRESS, b"att_pubs:" + cyc) or ctx.sget(
        STAKING_ADDRESS, b"prev_pubs"
    )
    prev_pubs = Reader(pubs_raw).bytes_list() if pubs_raw else []
    if not prev_pubs:
        return 0
    ctx.sput(STAKING_ADDRESS, b"att_done:" + cyc, b"\x01")
    raw = ctx.sget(STAKING_ADDRESS, b"att_checkin:" + cyc)
    voters = Reader(raw).bytes_list() if raw else []
    max_share = ATTENDANCE_CYCLE_REWARD // len(prev_pubs)
    from ..crypto.ecdsa import address_from_public_key

    for pub in prev_pubs:
        addr = address_from_public_key(pub)
        pen_key = b"penalty:" + addr
        penalty = int.from_bytes(
            ctx.sget(STAKING_ADDRESS, pen_key) or b"", "big"
        )
        if pub not in voters:
            penalty += max_share  # no-show: reward-sized penalty
        vkey = b"att_votes:" + cyc + pub
        votes_raw = ctx.sget(STAKING_ADDRESS, vkey) or b""
        votes = sorted(
            int.from_bytes(votes_raw[i : i + 4], "big")
            for i in range(0, len(votes_raw), 4)
        )
        if votes:
            mid = len(votes) // 2
            active = (
                (votes[mid - 1] + votes[mid]) // 2
                if len(votes) % 2 == 0
                else votes[mid]
            )
        else:
            active = 0
        reward = max_share * active // CYCLE_DURATION
        burn = min(penalty, reward)
        penalty -= burn
        reward -= burn
        if penalty:
            ctx.sput(STAKING_ADDRESS, pen_key, write_u256(penalty))
        else:
            ctx.sdel(STAKING_ADDRESS, pen_key)
        if reward:
            execution.set_balance(
                ctx.snap,
                addr,
                execution.get_balance(ctx.snap, addr) + reward,
            )
        ctx.sdel(STAKING_ADDRESS, vkey)
    # settle-time cleanup (reference ClearAttendanceDetectorCheckIns); the
    # done flag itself is kept for ATTENDANCE_SETTLE_LOOKBACK cycles so the
    # lazy sweep can tell "settled" from "orphaned", then swept
    ctx.sdel(STAKING_ADDRESS, b"att_checkin:" + cyc)
    ctx.sdel(STAKING_ADDRESS, b"att_pubs:" + cyc)
    if x > ATTENDANCE_SETTLE_LOOKBACK:
        ctx.sdel(
            STAKING_ADDRESS,
            b"att_done:" + write_u64(x - ATTENDANCE_SETTLE_LOOKBACK),
        )
    ctx.emit(STAKING_ADDRESS, b"attendance_finished" + cyc)
    return 1


def _get_winner_list(ctx, cycle: int) -> List[bytes]:
    raw = ctx.sget(STAKING_ADDRESS, b"winners:" + write_u64(cycle))
    if not raw:
        return []
    return Reader(raw).bytes_list()


def _put_winner_list(ctx, cycle: int, winners: List[bytes]) -> None:
    from ..utils.serialization import write_bytes_list

    ctx.sput(
        STAKING_ADDRESS, b"winners:" + write_u64(cycle), write_bytes_list(winners)
    )


# ---------------------------------------------------------------------------
# Governance (reference GovernanceContract.cs): keygen tx lifecycle + the
# validator-set change. The DKG math itself lives in consensus/keygen.py;
# these methods are the on-chain message board the keygen rides on.
# ---------------------------------------------------------------------------


def _is_next_validator(ctx: SystemContractContext) -> bool:
    """Sender gating for the keygen message board: only addresses of the
    LOTTERY-ELECTED set may post (reference GovernanceContract keygen
    methods check the sender against the cycle's validator set,
    GovernanceContract.cs:117-217). Without this, any funded address could
    sybil n-f confirms and install an attacker validator set."""
    from ..crypto import ecdsa as _ecdsa

    nv_raw = ctx.sget(STAKING_ADDRESS, b"next_validators")
    if not nv_raw:
        return False
    for pub in Reader(nv_raw).bytes_list():
        if _ecdsa.address_from_public_key(pub) == ctx.sender:
            return True
    return False


def governance(ctx: SystemContractContext, sel: bytes, args: Reader) -> Tuple[int, bytes]:
    if sel == SEL_KEYGEN_COMMIT:
        if not _is_next_validator(ctx):
            return 0, b""
        blob = args.bytes_()
        key = b"commit:" + write_u64(ctx.block // CYCLE_DURATION) + ctx.sender
        ctx.sput(GOVERNANCE_ADDRESS, key, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_commit" + ctx.sender + blob)
        return 1, b""
    if sel == SEL_KEYGEN_SEND_VALUE:
        if not _is_next_validator(ctx):
            return 0, b""
        round_no = args.u256()
        blob = args.bytes_()
        key = (
            b"value:"
            + write_u64(ctx.block // CYCLE_DURATION)
            + write_u64(round_no & 0xFFFFFFFFFFFFFFFF)
            + ctx.sender
        )
        ctx.sput(GOVERNANCE_ADDRESS, key, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_value" + ctx.sender + blob)
        return 1, b""
    if sel == SEL_KEYGEN_CONFIRM:
        if not _is_next_validator(ctx):
            return 0, b""
        blob = args.bytes_()  # serialized new public key set
        cycle = ctx.block // CYCLE_DURATION
        h = keccak256(blob)
        cnt_key = b"confirms:" + write_u64(cycle) + h
        raw = ctx.sget(GOVERNANCE_ADDRESS, cnt_key)
        voters = Reader(raw).bytes_list() if raw else []
        if ctx.sender in voters:
            return 0, b""
        voters.append(ctx.sender)
        from ..utils.serialization import write_bytes_list

        ctx.sput(GOVERNANCE_ADDRESS, cnt_key, write_bytes_list(voters))
        ctx.sput(GOVERNANCE_ADDRESS, b"candidate:" + h, blob)
        ctx.emit(GOVERNANCE_ADDRESS, b"keygen_confirm" + ctx.sender)
        # N-F matching confirms from the elected set finalize the rotation
        # (reference GovernanceContract.Confirm -> ChangeValidators,
        # GovernanceContract.cs:283-331)
        nv_raw = ctx.sget(STAKING_ADDRESS, b"next_validators")
        if nv_raw:
            n_next = len(Reader(nv_raw).bytes_list())
            f_next = (n_next - 1) // 3
            if len(voters) >= n_next - f_next:
                ctx.sput(GOVERNANCE_ADDRESS, b"pending_validators", blob)
                ctx.emit(GOVERNANCE_ADDRESS, b"validators_changed" + h)
        return 1, write_u32(len(voters))
    if sel == SEL_CHANGE_VALIDATORS:
        # In the reference this is an internal transition invoked by the
        # confirm threshold (GovernanceContract.cs:283-331), never a public
        # entry point; exposing it lets one funded address install an
        # arbitrary validator set. The only path to pending_validators is
        # the n-f keygen-confirm quorum above.
        return 0, b""
    if sel == SEL_FINISH_CYCLE:
        # only the cycle's LAST block may rotate the set: the new keys are
        # wallet-installed from era (cycle+1)*CYCLE_DURATION, so the
        # validator-set flip must land in the snapshot of exactly the block
        # before (reference injects FinishCycle as a cycle-boundary system
        # tx, BlockProducer.cs:126-146)
        if ctx.block % CYCLE_DURATION != CYCLE_DURATION - 1:
            return 0, b""
        pending = ctx.sget(GOVERNANCE_ADDRESS, b"pending_validators")
        if pending:
            outgoing = ctx.snap.get("validators", b"current")
            ctx.snap.put("validators", b"current", pending)
            ctx.sdel(GOVERNANCE_ADDRESS, b"pending_validators")
            # next cycle's attendance-detection electorate is the OUTGOING
            # set — the validators who served the cycle being judged
            # (reference captures _previousValidatorPubKeys from the
            # pre-rotation snapshot). When the genesis set was still active
            # (`outgoing` is None) prev_pubs already holds it. The NEW
            # set's pub->address mappings register now so its members can
            # submit once they become the electorate.
            try:
                from ..consensus.keys import PublicConsensusKeys
                from ..crypto.ecdsa import address_from_public_key
                from ..utils.serialization import write_bytes_list

                if outgoing is not None:
                    # preserve the electorate of any cycle whose attendance
                    # close tx hasn't landed yet: once prev_pubs rotates,
                    # a lazy finishAttendanceDetection for those cycles
                    # needs the set they actually voted with
                    cyc_now = ctx.block // CYCLE_DURATION
                    prev_raw = ctx.sget(STAKING_ADDRESS, b"prev_pubs")
                    wm_raw = ctx.sget(STAKING_ADDRESS, b"att_settled")
                    wm = int.from_bytes(wm_raw, "big") if wm_raw else None
                    if prev_raw is not None:
                        for x in range(
                            max(1, cyc_now - ATTENDANCE_SETTLE_LOOKBACK + 1),
                            cyc_now + 1,
                        ):
                            cyc_key = write_u64(x)
                            if ctx.sget(
                                STAKING_ADDRESS, b"att_done:" + cyc_key
                            ) or ctx.sget(
                                STAKING_ADDRESS, b"att_pubs:" + cyc_key
                            ):
                                continue
                            if wm is not None:
                                if x <= wm:
                                    continue  # settled pre-cleanup
                            elif x != cyc_now and not ctx.sget(
                                STAKING_ADDRESS, b"att_checkin:" + cyc_key
                            ):
                                continue  # pre-watermark transition
                            ctx.sput(
                                STAKING_ADDRESS,
                                b"att_pubs:" + cyc_key,
                                prev_raw,
                            )
                    out_keys = PublicConsensusKeys.decode(outgoing)
                    ctx.sput(
                        STAKING_ADDRESS,
                        b"prev_pubs",
                        write_bytes_list(list(out_keys.ecdsa_pub_keys)),
                    )
                new_keys = PublicConsensusKeys.decode(pending)
                for pub in new_keys.ecdsa_pub_keys:
                    ctx.sput(
                        STAKING_ADDRESS,
                        b"pub:" + address_from_public_key(pub),
                        pub,
                    )
            except Exception:
                pass  # undecodable candidate cannot block the rotation
            ctx.emit(GOVERNANCE_ADDRESS, b"cycle_finished")
            return 1, b""
        return 0, b""
    return 0, b""


# ---------------------------------------------------------------------------
# Registry / dispatcher (reference ContractRegisterer.cs)
# ---------------------------------------------------------------------------


def dispatch(
    snap: Snapshot,
    sender: bytes,
    tx: Transaction,
    block: int,
    tx_hash: Optional[bytes] = None,
) -> Tuple[int, bytes]:
    ctx = SystemContractContext(snap, sender, tx, block)
    data = tx.invocation
    if len(data) < 4:
        return 0, b""
    sel, rest = data[:4], Reader(data[4:])
    try:
        if tx.to == DEPLOY_ADDRESS and sel == SEL_DEPLOY:
            result = deploy_contract(ctx, rest)
        elif tx.to == NATIVE_TOKEN_ADDRESS:
            result = native_token(ctx, sel, rest)
        elif tx.to == STAKING_ADDRESS:
            result = staking(ctx, sel, rest)
        elif tx.to == GOVERNANCE_ADDRESS:
            result = governance(ctx, sel, rest)
        else:
            return 0, b""
    except (ValueError, AssertionError):
        return 0, b""
    # persist emitted events so node services (KeyGenManager) can react to
    # executed system txs (reference: BlockManager.OnSystemContractInvoked,
    # BlockManager.cs:171-176, 547-560)
    if result[0] == 1 and tx_hash is not None:
        from ..utils.serialization import write_u32 as _u32

        for i, (contract, payload) in enumerate(ctx.events):
            snap.put("events", tx_hash + _u32(i), contract + payload)
    return result


SYSTEM_CONTRACTS: Dict[bytes, Callable] = {
    addr: dispatch
    for addr in (
        DEPLOY_ADDRESS,
        NATIVE_TOKEN_ADDRESS,
        GOVERNANCE_ADDRESS,
        STAKING_ADDRESS,
    )
}


def register_genesis_validators(snap: Snapshot, pubkeys: List[bytes]) -> None:
    """Seed the attendance-detection electorate at genesis: the staking
    contract's `prev_pubs` list plus the pub->address mapping for each
    genesis validator (a rotation later overwrites both at FinishCycle).
    Reference analogue: genesis validators enter _previousValidatorPubKeys
    via config, config_mainnet.json validators."""
    from ..crypto.ecdsa import address_from_public_key
    from ..utils.serialization import write_bytes_list

    snap.put(
        "storage",
        _skey(STAKING_ADDRESS, b"prev_pubs"),
        write_bytes_list(list(pubkeys)),
    )
    for pub in pubkeys:
        snap.put(
            "storage",
            _skey(STAKING_ADDRESS, b"pub:" + address_from_public_key(pub)),
            pub,
        )


def make_executer(chain_id: int) -> execution.TransactionExecuter:
    """TransactionExecuter wired with the system-contract registry."""
    return execution.TransactionExecuter(
        chain_id,
        system_contracts=dict(SYSTEM_CONTRACTS),
    )
