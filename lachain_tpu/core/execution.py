"""Transaction execution over state snapshots.

Parity with the reference's execution path
(/root/reference/src/Lachain.Core/Blockchain/Operations/TransactionManager.cs:88-140
and TransactionExecuter.cs:1-153): per-tx signature/nonce/balance checks,
native transfers, system-contract dispatch, receipts into the transactions
subtree.

The reference wraps every tx in snapshot/approve/rollback
(BlockManager._Execute, BlockManager.cs:371-560); here a failed tx simply
discards its buffered writes — the functional snapshot makes the rollback
trick free.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..storage.state import Snapshot
from ..utils.serialization import write_u32, write_u64, write_u256
from .types import (
    SignedTransaction,
    TransactionReceipt,
    ZERO_ADDRESS,
)

GAS_PER_TX = 21000  # base transfer cost (reference GasMetering.cs)

_BALANCE = b"b:"
_NONCE = b"n:"


def get_balance(snap: Snapshot, addr: bytes) -> int:
    raw = snap.get("balances", _BALANCE + addr)
    return int.from_bytes(raw, "big") if raw else 0


def set_balance(snap: Snapshot, addr: bytes, value: int) -> None:
    snap.put("balances", _BALANCE + addr, write_u256(value))


def get_nonce(snap: Snapshot, addr: bytes) -> int:
    raw = snap.get("balances", _NONCE + addr)
    return int.from_bytes(raw, "big") if raw else 0


def set_nonce(snap: Snapshot, addr: bytes, value: int) -> None:
    snap.put("balances", _NONCE + addr, write_u64(value))


@dataclass
class ExecutionResult:
    receipt: TransactionReceipt
    ok: bool


class TransactionExecuter:
    """Executes one signed transaction against a snapshot."""

    def __init__(self, chain_id: int, system_contracts=None):
        self.chain_id = chain_id
        # address -> callable(snap, sender, tx, block_index) -> (status, ret)
        self.system_contracts = system_contracts or {}

    def execute(
        self,
        snap: Snapshot,
        stx: SignedTransaction,
        block_index: int,
        index_in_block: int,
    ) -> ExecutionResult:
        tx_hash = stx.hash()

        def receipt(
            status: int, sender: bytes, ret: bytes = b"", gas: int = GAS_PER_TX
        ) -> ExecutionResult:
            r = TransactionReceipt(
                tx_hash=tx_hash,
                block_index=block_index,
                index_in_block=index_in_block,
                gas_used=gas,
                status=status,
                sender=sender,
                return_data=ret,
            )
            snap.put("transactions", tx_hash, r.encode())
            return ExecutionResult(receipt=r, ok=status == 1)

        sender = stx.sender(self.chain_id)
        if sender is None:
            return receipt(0, ZERO_ADDRESS)
        tx = stx.tx
        if get_nonce(snap, sender) != tx.nonce:
            return receipt(0, sender)
        fee = GAS_PER_TX * tx.gas_price
        bal = get_balance(snap, sender)
        if bal < tx.value + fee:
            return receipt(0, sender)
        # effects; a failed call rolls back everything except the consumed
        # nonce and fee (reference per-tx snapshot/rollback loop,
        # BlockManager.cs:371-560)
        cp = snap.checkpoint()
        set_nonce(snap, sender, tx.nonce + 1)
        set_balance(snap, sender, bal - tx.value - fee)
        if tx.to in self.system_contracts:
            handler = self.system_contracts[tx.to]
            try:
                status, ret = handler(
                    snap, sender, tx, block_index, tx_hash=tx_hash
                )
            except Exception:
                status, ret = 0, b""
            if status != 1:
                snap.restore(cp)
                set_nonce(snap, sender, tx.nonce + 1)
                set_balance(snap, sender, bal - fee)
                return receipt(0, sender, ret)
            set_balance(snap, tx.to, get_balance(snap, tx.to) + tx.value)
            return receipt(status, sender, ret)
        # deployed WASM contract call (reference TransactionExecuter.cs ->
        # ContractInvoker.Invoke -> VirtualMachine.InvokeWasmContract)
        from ..vm import vm as wasm_vm

        if tx.invocation and wasm_vm.get_code(snap, tx.to) is not None:
            # the full gas limit must be payable up front: metered work is
            # charged even when the call reverts (reference gas accounting —
            # BlockManager._Execute collects gas on failed receipts too)
            if bal < tx.value + tx.gas_limit * tx.gas_price:
                snap.restore(cp)
                set_nonce(snap, sender, tx.nonce + 1)
                set_balance(snap, sender, bal - fee)
                return receipt(0, sender)
            set_balance(snap, tx.to, get_balance(snap, tx.to) + tx.value)
            machine = wasm_vm.VirtualMachine(
                snap,
                block_index=block_index,
                origin=sender,
                gas_price=tx.gas_price,
                chain_id=self.chain_id,
            )
            res = machine.invoke_contract(
                contract=tx.to,
                sender=sender,
                value=tx.value,
                input=tx.invocation,
                gas_limit=max(0, tx.gas_limit - GAS_PER_TX),
            )
            # never bill beyond the up-front-verified gas limit: the meter
            # clamps spent to its limit, and this min() guards against any
            # residual overshoot so the sender balance cannot go negative
            gas_total = min(GAS_PER_TX + res.gas_used, tx.gas_limit)
            if res.status != 1:
                snap.restore(cp)
                set_nonce(snap, sender, tx.nonce + 1)
                set_balance(snap, sender, bal - gas_total * tx.gas_price)
                return receipt(0, sender, res.return_data, gas=gas_total)
            set_balance(
                snap,
                sender,
                get_balance(snap, sender) - res.gas_used * tx.gas_price,
            )
            for i, (contract, data) in enumerate(res.events):
                snap.put("events", tx_hash + write_u32(i), contract + data)
            return receipt(1, sender, res.return_data, gas=gas_total)
        set_balance(snap, tx.to, get_balance(snap, tx.to) + tx.value)
        return receipt(1, sender)
