"""Block emulate/execute/commit state machine.

Parity with the reference's BlockManager
(/root/reference/src/Lachain.Core/Blockchain/Operations/BlockManager.cs):
  * Emulate — execute txs and compute the resulting state hash WITHOUT
    committing (the reference does a rollback trick, BlockManager.cs:231-267;
    functional snapshots make this free)
  * Execute(commit, checkStateHash) — the canonical per-tx loop (304-560)
  * block persistence + height index (BlockPersisted role)
  * genesis building (Blockchain/Genesis/GenesisBuilder.cs:14-76)

Determinism invariant (SURVEY.md §7 hard part #5): emulate and execute run
the SAME pure function over the same base roots, so the state hash a
validator signs in its header is exactly what executing the block produces.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..storage.kv import EntryPrefix, KVStore, prefixed
from ..storage.state import StateManager, StateRoots
from ..utils import metrics
from ..utils import bloom
from ..utils import tracing
from ..utils import txtrace
from ..utils.serialization import write_u32, write_u64
from .execution import TransactionExecuter, set_balance
from .parallel_exec import (
    MIN_PARALLEL_TXS,
    execute_block_parallel,
    resolve_lanes,
)
from .types import (
    Block,
    BlockHeader,
    MultiSig,
    SignedTransaction,
    ZERO_HASH,
    tx_merkle_root,
    warm_sender_caches,
)


@dataclass
class EmulationResult:
    roots: StateRoots
    state_hash: bytes
    receipts: List
    # 20-byte emitting-contract addresses of THIS block's events, captured
    # from the snapshot write buffer before freeze — _persist builds the
    # per-block log bloom from these instead of probing the trie per tx
    event_addrs: Tuple[bytes, ...] = ()


# process-wide emulation memo: key -> (EmulationResult, exported trie node
# buffer); bounded FIFO. See BlockManager.emulate for the sharing argument.
# Lock-guarded: parallel-execution lane workers and the pipelined-era
# scheduler can emulate from different threads concurrently.
_EMULATE_MEMO: Dict[tuple, Tuple[EmulationResult, dict]] = {}
_EMULATE_MEMO_MAX = 8
_EMULATE_MEMO_LOCK = threading.Lock()


class BlockManager:
    def __init__(
        self,
        kv: KVStore,
        state: StateManager,
        executer: TransactionExecuter,
        lanes: int = 1,
    ):
        self._kv = kv
        self.state = state
        self.executer = executer
        # execution.lanes knob: 1 pins the serial oracle (default), N>1
        # fixes the lane count, 0 = auto (cores, capped). Results are
        # bit-identical either way (core/parallel_exec.py).
        self.lanes = max(int(lanes), 0)
        self.on_block_persisted = []  # callbacks(block)

    # -- ordering (deterministic across validators) ---------------------------
    @staticmethod
    def order_transactions(
        txs: Sequence[SignedTransaction], chain_id: int
    ) -> List[SignedTransaction]:
        """Canonical execution order: (sender, nonce, hash) — every honest
        node derives the identical order from the agreed tx set
        (role of the reference's fee-ordering in BlockProducer.CreateHeader)."""
        return sorted(
            txs,
            key=lambda stx: (
                stx.sender(chain_id) or b"\xff" * 20,
                stx.tx.nonce,
                stx.hash(),
            ),
        )

    # -- emulate --------------------------------------------------------------
    def emulate(
        self,
        txs: Sequence[SignedTransaction],
        block_index: int,
        base: Optional[StateRoots] = None,
    ) -> EmulationResult:
        # emulate is a pure function of (base roots, index, chain id,
        # ordered txs). It runs redundantly in two directions: the reference
        # pays it twice per produced block on ONE node (CreateHeader
        # emulates, Execute emulates again to check the signed state hash,
        # BlockManager.cs:231-267 vs 304-560), and an in-process
        # multi-validator harness additionally makes every node emulate the
        # SAME agreed tx set over identical base roots. A process-wide memo
        # on the exact purity key collapses both. Correctness of sharing
        # across BlockManager instances: the base state hash pins the full
        # chain state, so any two tries with that base hold bit-identical
        # node sets; the producing trie's write-back buffer is exported with
        # the result and absorbed on hit, so the consumer's commit persists
        # exactly the nodes its own freeze would have buffered.
        base_roots = base if base is not None else self.state.committed
        key = (
            base_roots.state_hash(),
            block_index,
            self.executer.chain_id,
            tuple(stx.hash() for stx in txs),
        )
        with _EMULATE_MEMO_LOCK:
            hit = _EMULATE_MEMO.get(key)
        if hit is not None:
            em, nodes = hit
            self.state.trie.absorb_pending(nodes)
            return em
        lanes = resolve_lanes(self.lanes)
        with tracing.span("exec.block", cat="exec", era=block_index):
            if lanes > 1 and len(txs) >= MIN_PARALLEL_TXS:
                snap, receipts, _stats = execute_block_parallel(
                    self.executer,
                    self.state,
                    txs,
                    block_index,
                    base_roots,
                    lanes,
                )
            else:
                snap = self.state.new_snapshot(base_roots)
                receipts = []
                for i, stx in enumerate(txs):
                    res = self.executer.execute(snap, stx, block_index, i)
                    receipts.append(res.receipt)
            event_addrs = tuple(
                v[:20] for v in snap._writes["events"].values() if v
            )
            # merkle nests inside exec.block and outranks it in the phase
            # report: commit attribution separates hashing from execution
            with tracing.span("merkle.freeze", cat="merkle", era=block_index):
                roots = snap.freeze()
        em = EmulationResult(
            roots=roots,
            state_hash=roots.state_hash(),
            receipts=receipts,
            event_addrs=event_addrs,
        )
        with _EMULATE_MEMO_LOCK:
            _EMULATE_MEMO[key] = (em, self.state.trie.export_pending())
            while len(_EMULATE_MEMO) > _EMULATE_MEMO_MAX:
                _EMULATE_MEMO.pop(next(iter(_EMULATE_MEMO)))
        return em

    # -- execute + commit ------------------------------------------------------
    def execute_block(
        self,
        header: BlockHeader,
        txs: Sequence[SignedTransaction],
        multisig: MultiSig,
        check_state_hash: bool = True,
    ) -> Block:
        # block exec metrics (reference Prometheus summaries,
        # BlockManager.cs:62-127)
        with metrics.measure("block_execute"):
            # batch-recover every sender up front (threaded native entry);
            # ordering + execution then hit warm caches only
            warm_sender_caches(txs, self.executer.chain_id)
            txs = self.order_transactions(txs, self.executer.chain_id)
            # tx lifecycle: execution reached this block (stamped before
            # emulate so a memo hit — block already emulated during header
            # creation — still marks when THIS node's execute touched it)
            txtrace.stamp_many(
                (stx.hash() for stx in txs), "exec", era=header.index
            )
            em = self.emulate(txs, header.index)
            if check_state_hash and em.state_hash != header.state_hash:
                raise ValueError(
                    f"state hash mismatch at block {header.index}: "
                    f"{em.state_hash.hex()} != {header.state_hash.hex()}"
                )
            if tx_merkle_root([t.hash() for t in txs]) != header.merkle_root:
                raise ValueError("tx merkle root mismatch")
            block = Block(
                header=header,
                tx_hashes=tuple(t.hash() for t in txs),
                multisig=multisig,
            )
            self._persist(block, txs, em)
        metrics.set_gauge("chain_height", block.header.index)
        metrics.inc("chain_txs_total", len(txs))
        return block

    def _persist(self, block: Block, txs, em: EmulationResult) -> None:
        from ..storage.crashpoints import crash_point

        crash_point("block.persist.pre")
        h = block.hash()
        puts = [
            (prefixed(EntryPrefix.BLOCK_BY_HASH, h), block.encode()),
            (
                prefixed(
                    EntryPrefix.BLOCK_HASH_BY_HEIGHT,
                    write_u64(block.header.index),
                ),
                h,
            ),
        ]
        for stx in txs:
            puts.append(
                (
                    prefixed(EntryPrefix.TRANSACTION_BY_HASH, stx.hash()),
                    stx.encode(),
                )
            )
        # address -> tx index (sender and recipient): serves the fe_*
        # account-history RPC family (reference FrontEndService.cs) without
        # chain scans. Key: prefix | address | height | index-in-block.
        for i, stx in enumerate(txs):
            th = stx.hash()
            key_tail = write_u64(block.header.index) + write_u32(i)
            touched = {stx.tx.to}
            sender = stx.sender(self.executer.chain_id)
            if sender is not None:
                touched.add(sender)
            for addr in touched:
                puts.append(
                    (
                        prefixed(EntryPrefix.ADDRESS_TX, addr + key_tail),
                        th,
                    )
                )
        # per-block log bloom over emitting addresses: eth_getLogs and the
        # filter machinery skip non-matching blocks without decoding events
        # (reference: Misc/BloomFilter.cs). The emulation captured the
        # block's emitting addresses from its write buffer, so the bloom
        # costs |events| adds instead of a trie probe per (tx, event index)
        bl = bloom.empty()
        for addr in em.event_addrs:
            bloom.add(bl, addr)
        puts.append(
            (
                prefixed(
                    EntryPrefix.BLOCK_BLOOM, write_u64(block.header.index)
                ),
                bytes(bl),
            )
        )
        self._kv.write_batch(puts)
        # the torn-block window: the block batch is durable but the state
        # commit (trie nodes + snapshot index + tip) is not — a crash here
        # leaves an orphan block above the tip, which fsck must detect
        crash_point("block.persist.mid")
        self.state.commit(block.header.index, em.roots)
        crash_point("block.persist.post")
        # tx lifecycle terminal stamp: the block holding the tx is durable
        # (also closes tx_e2e_seconds for sampled txs)
        txtrace.stamp_many(
            block.tx_hashes, "commit", era=block.header.index
        )
        for cb in list(self.on_block_persisted):
            cb(block)

    # -- reads ----------------------------------------------------------------
    def block_by_height(self, height: int) -> Optional[Block]:
        h = self._kv.get(
            prefixed(EntryPrefix.BLOCK_HASH_BY_HEIGHT, write_u64(height))
        )
        if h is None:
            return None
        return self.block_by_hash(h)

    def block_by_hash(self, h: bytes) -> Optional[Block]:
        enc = self._kv.get(prefixed(EntryPrefix.BLOCK_BY_HASH, h))
        return Block.decode(enc) if enc else None

    def transaction_by_hash(self, h: bytes) -> Optional[SignedTransaction]:
        enc = self._kv.get(prefixed(EntryPrefix.TRANSACTION_BY_HASH, h))
        return SignedTransaction.decode(enc) if enc else None

    def transactions_by_address(
        self, addr: bytes, limit: int = 100, before_height: Optional[int] = None
    ) -> list:
        """Most-recent-first tx hashes touching `addr` (sender or
        recipient), paginated by height. Requires the KV store to support
        prefix scans (both backends do)."""
        prefix = prefixed(EntryPrefix.ADDRESS_TX, addr)
        out = []
        for key, th in self._kv.scan_prefix(prefix):
            height = int.from_bytes(key[len(prefix) : len(prefix) + 8], "big")
            if before_height is not None and height >= before_height:
                continue
            out.append((height, th))
        out.sort(reverse=True)
        return [(h, th) for h, th in out[:limit]]

    def bloom_by_height(self, height: int) -> Optional[bytes]:
        return self._kv.get(
            prefixed(EntryPrefix.BLOCK_BLOOM, write_u64(height))
        )

    def receipt_by_hash(self, h: bytes) -> Optional[bytes]:
        snap = self.state.new_snapshot()
        return snap.get("transactions", h)

    def current_height(self) -> int:
        h = self.state.committed_height()
        return h if h is not None else -1

    # -- genesis ---------------------------------------------------------------
    def build_genesis(
        self,
        initial_balances: Dict[bytes, int],
        chain_id: int,
        validator_pubs: Optional[List[bytes]] = None,
    ) -> Block:
        """Reference: GenesisBuilder.cs:14-76 — block 0 with funded accounts
        and the genesis validator set registered with the staking contract
        (the attendance-detection electorate)."""
        if self.block_by_height(0) is not None:
            return self.block_by_height(0)
        snap = self.state.new_snapshot(StateRoots())
        for addr, bal in sorted(initial_balances.items()):
            set_balance(snap, addr, bal)
        if validator_pubs:
            from . import system_contracts as _sc

            _sc.register_genesis_validators(snap, list(validator_pubs))
        roots = snap.freeze()
        header = BlockHeader(
            index=0,
            prev_block_hash=ZERO_HASH,
            merkle_root=ZERO_HASH,
            state_hash=roots.state_hash(),
            nonce=0,
        )
        block = Block(header=header, tx_hashes=(), multisig=MultiSig(()))
        em = EmulationResult(roots=roots, state_hash=roots.state_hash(), receipts=[])
        self._persist(block, [], em)
        return block
