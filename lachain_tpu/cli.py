"""lachain-tpu operator CLI: the runnable node process.

Parity with the reference's console
(/root/reference/src/Lachain.Console/Program.cs:23-47 verbs,
TrustedKeygen.cs:56-66 devnet generation, Application.cs:67-198 service
composition):

  lachain-tpu keygen --n 4 --f 1 --out netdir [--port-base 7070]
      trusted-dealer devnet generation: writes config{i}.json +
      wallet{i}.json for every validator, cross-wired as peers.
  lachain-tpu run --config netdir/config0.json
      boots a full node from a config: wallet, network, sync, RPC, and
      the autonomous era lifecycle.
  lachain-tpu height --config netdir/config0.json
      one-shot local status (height + validator set) without RPC.
  lachain-tpu db shrink|rollback|compact|export|import --config ...
      offline store maintenance (prune checkpoints / restore a snapshot /
      LSM full merge / engine-portable dump + load — the sqlite<->lsm
      migration path; reference `db` verb + --RollBackTo,
      Application.cs:119-127).
  lachain-tpu encrypt|decrypt --wallet ...
      wallet re-keying / decrypted inspection (reference encrypt/decrypt).
  lachain-tpu console --rpc http://127.0.0.1:7071
      interactive operator shell attached to a LIVE node over its RPC
      (role of the reference's in-process console, CLI/ConsoleManager.cs:14
      + ConsoleCommands.cs:20; attaching over RPC means the shell works
      against any reachable node, containers included).
  lachain-tpu chaos --drop 0.1 --crash 3@50:400 --partition 0,1|2,3@30:500
      seeded fault-injection run against an in-process devnet: eras under
      message loss / crash / partition schedules, with an era-by-era
      recovery report. Same seed -> same faults -> same chain, so a
      production failure replays from its seed (DEPLOY.md, Failure
      handling).
  lachain-tpu chaos --crash-point block.persist.mid
      storage crash scenario: a child process runs the deterministic
      commit workload and is SIGKILLed at the named pipeline point; the
      parent fscks the torn database, repairs, and verifies a resumed run
      completes (DEPLOY.md, Crash recovery).
  lachain-tpu fleet-upgrade --n 6 --wan 'regions=us,eu;default=40ms/5ms'
      zero-downtime rolling-upgrade drill: an in-process TCP fleet boots
      on the legacy (pre-handshake) wire, optionally WAN-shaped into
      emulated regions, and rolls node-by-node onto the LTRX versioned
      wire under paced traffic. Gated on /healthz staying ok and zero
      fleet missed eras; prints a compare.py-readable JSON result
      (DEPLOY.md, WAN operations & rolling upgrades).
  lachain-tpu fsck --config netdir/config0.json [--deep] [--no-repair]
      storage invariant scan: detects torn states (orphan block, lost
      state roots, stale journal eras), repairs what is safely repairable.
      Exit 0 = clean or repaired; 1 = refused (operator runbook in
      DEPLOY.md); 2 = no database.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import secrets
import signal
import sys
from typing import List

logger = logging.getLogger("lachain_tpu.cli")


# ---------------------------------------------------------------------------
# keygen
# ---------------------------------------------------------------------------


def cmd_keygen(args) -> int:
    from .consensus.keys import trusted_key_gen
    from .core.config import CURRENT_VERSION
    from .core.vault import PrivateWallet
    from .crypto import ecdsa

    n, f = args.n, args.f
    if n <= 3 * f:
        print(f"need n > 3f (got n={n}, f={f})", file=sys.stderr)
        return 2
    os.makedirs(args.out, exist_ok=True)
    pub, privs = trusted_key_gen(n, f)
    peers: List[str] = []
    for i in range(n):
        port = args.port_base + 2 * i
        peers.append(
            f"{args.host}:{port}:{pub.ecdsa_pub_keys[i].hex()}"
        )
    balances = {}
    for i in range(n):
        addr = ecdsa.address_from_public_key(pub.ecdsa_pub_keys[i])
        balances["0x" + addr.hex()] = str(args.initial_balance)
    for extra in args.fund or []:
        balances[extra] = str(args.initial_balance)
    consensus_hex = pub.encode().hex()
    regions = (
        [r.strip() for r in args.regions.split(",") if r.strip()]
        if getattr(args, "regions", None)
        else []
    )
    for i in range(n):
        wallet_path = os.path.join(args.out, f"wallet{i}.json")
        password = secrets.token_hex(8) if args.encrypt else ""
        wallet = PrivateWallet(
            path=wallet_path,
            password=password,
            ecdsa_priv=privs[i].ecdsa_priv,
        )
        wallet.add_threshold_keys(0, privs[i].tpke_priv, privs[i].ts_share)
        wallet.save()
        if password:
            # never written to the config: hand it to the operator once;
            # `run` reads LACHAIN_WALLET_PASSWORD at startup
            print(f"wallet{i} password: {password}", file=sys.stderr)
        cfg = {
            "version": CURRENT_VERSION,
            "network": {
                "host": args.host,
                "port": args.port_base + 2 * i,
                "peers": [p for j, p in enumerate(peers) if j != i],
            },
            "genesis": {
                "chainId": args.chain_id,
                "balances": balances,
                "consensusKeys": consensus_hex,
                "validatorIndex": i,
            },
            "vault": {"path": wallet_path, "password": ""},
            "staking": {
                "cycleDuration": args.cycle_duration,
                "vrfSubmissionPhase": args.vrf_phase,
                "attendanceDetectionDuration": max(
                    min(100, args.cycle_duration // 5), 1
                ),
            },
            "rpc": {
                "enabled": True,
                "host": "127.0.0.1",
                "port": args.port_base + 2 * i + 1,
                "apiKey": None,
            },
            "blockchain": {"targetTxsPerBlock": 1000, "targetBlockTimeMs": args.block_time_ms},
            # fresh chains activate every current hardfork from genesis —
            # written EXPLICITLY so the chain's schedule never depends on
            # library defaults (migrated configs get the NEVER sentinel
            # instead, core/config.py _v5_to_v6)
            "hardfork": {"heights": {"fast_wasm_gas": 0}},
            # written explicitly for the same reason: the engine a chain's
            # database is created with is permanent (migrated <=v6 configs
            # get sqlite pinned instead, core/config.py _v6_to_v7)
            "storage": {"engine": "lsm"},
        }
        # WAN emulation knobs are additive network keys: `region` labels the
        # node's emulated region (round-robin over --regions, matching
        # LinkShaper's positional striping), `wanShaper` carries the shared
        # LinkShaper spec so the emulated matrix is fleet-wide consistent
        if regions:
            cfg["network"]["region"] = regions[i % len(regions)]
        if getattr(args, "wan", None):
            cfg["network"]["wanShaper"] = args.wan
        path = os.path.join(args.out, f"config{i}.json")
        with open(path, "w") as fh:
            json.dump(cfg, fh, indent=2, sort_keys=True)
        print(path)
    return 0


# ---------------------------------------------------------------------------
# run
# ---------------------------------------------------------------------------


def _build_node(cfg, config_path=None):
    from .consensus.keys import PrivateConsensusKeys, PublicConsensusKeys
    from .core import system_contracts as sc
    from .core.hardforks import set_hardfork_heights
    from .core.node import Node
    from .core.vault import PrivateWallet
    from .network.hub import PeerAddress
    from .storage.kv import SqliteKV
    from .storage.lsm import LsmKV

    sc.set_cycle_params(
        cfg.staking.cycle_duration,
        cfg.staking.vrf_submission_phase,
        cfg.staking.attendance_detection_duration,
    )
    if cfg.hardfork.heights:
        set_hardfork_heights(cfg.hardfork.heights, force=True)
    if cfg.trace_capacity is not None:
        # resize the merged rings now; native engines created after this
        # point (LSM store below, consensus engine per era) size their
        # in-engine rings from the same knob via tracing.DEFAULT_CAPACITY
        from .utils import tracing

        tracing.DEFAULT_CAPACITY = max(int(cfg.trace_capacity), 0)
        tracing.set_capacity(max(tracing.DEFAULT_CAPACITY, 1))
    if cfg.tx_sample_shift is not None:
        # tx lifecycle sampling density: 1-in-2^shift transactions carry
        # stage stamps (observability.txSampleShift; 0 = stamp every tx)
        from .utils import txtrace

        txtrace.set_sample_shift(int(cfg.tx_sample_shift))
    password = cfg.vault.password or os.environ.get(
        "LACHAIN_WALLET_PASSWORD", ""
    )
    wallet = PrivateWallet.load(cfg.vault.path, password)
    pub = PublicConsensusKeys.decode(bytes.fromhex(cfg.genesis.consensus_keys))
    idx = cfg.genesis.validator_index
    priv = wallet.consensus_keys_for_era(0)
    if priv is None or idx < 0:
        priv = PrivateConsensusKeys.observer(wallet.ecdsa_priv)
        idx = -1
    balances = {
        bytes.fromhex(a[2:]): int(v) for a, v in cfg.genesis.balances.items()
    }
    db_path = cfg.storage_path
    if db_path is None and config_path is not None:
        db_path = os.path.splitext(config_path)[0] + ".db"
    node = Node(
        index=idx,
        public_keys=pub,
        private_keys=priv,
        chain_id=cfg.genesis.chain_id,
        kv=(
            (LsmKV if cfg.storage_engine == "lsm" else SqliteKV)(db_path)
            if db_path
            else None
        ),
        host=cfg.network.host,
        port=cfg.network.port,
        advertise_host=cfg.network.advertise_host,
        relay=cfg.network.relay,
        initial_balances=balances,
        txs_per_block=cfg.blockchain.target_txs_per_block,
        wallet=wallet,
        block_interval=cfg.blockchain.target_block_time_ms / 1000.0,
        pipeline_window=cfg.blockchain.pipeline_window,
        exec_lanes=cfg.execution_lanes,
        merkle_workers=cfg.merkle_workers,
    )
    if cfg.idle_alert_fraction is not None:
        # observability.idleAlertFraction: /healthz reads degraded when
        # the rolling era idle fraction exceeds this
        node.idle_alert_fraction = float(cfg.idle_alert_fraction)
    if cfg.wan_shaper:
        # network.wanShaper: emulated WAN matrix on this node's outbound
        # frames (network/faults.py LinkShaper). Every node in the fleet
        # carries the same spec, so the pairwise latency/bandwidth matrix
        # is consistent even though each node only shapes its own sends.
        # Validator indices are the shaper's node ids — the same striping
        # keygen --regions writes into network.region.
        from .network.faults import FaultPlan, LinkShaper

        shaper = LinkShaper.parse(cfg.wan_shaper)
        node.network.install_faults(
            FaultPlan(seed=cfg.genesis.chain_id, shaper=shaper), idx
        )
        for j, vpub in enumerate(pub.ecdsa_pub_keys):
            node.network.map_fault_peer(vpub, j)
    peers = []
    for spec in cfg.network.peers:
        host, port, pubhex = spec.rsplit(":", 2)
        peers.append(
            PeerAddress(
                public_key=bytes.fromhex(pubhex), host=host, port=int(port)
            )
        )
    return node, peers


async def _run_node(cfg, args) -> None:
    node, peers = _build_node(cfg, args.config)
    want_fast = bool(getattr(args, "fast_sync", False)) and peers
    await node.start(start_synchronizer=not want_fast)
    node.connect(peers)
    if want_fast:
        # reference Application.Start: FastSynchronizerBatch BEFORE the
        # block synchronizer, so replay doesn't race the state download
        await asyncio.sleep(1.0)  # let peer connections establish
        checkpoint = getattr(args, "trusted_checkpoint", None)
        if checkpoint:
            height_s, hash_s = checkpoint.split(":", 1)
            node.fast_sync.trusted = (
                int(height_s),
                bytes.fromhex(hash_s.removeprefix("0x")),
            )
        try:
            # all configured peers form the serving set: the scheduler
            # spreads batches across them and fails over on its own
            h = await node.fast_sync.sync(
                [peer.public_key for peer in peers],
                timeout=120,
                snapshot=bool(getattr(args, "snapshot", False)),
            )
            print(f"fast-synced to height {h}", flush=True)
        except Exception as e:
            logger.warning("fast sync failed: %s", e)
        node.start_services()
    rpc = None
    if cfg.rpc.enabled:
        rpc = await node.start_rpc(
            cfg.rpc.host,
            cfg.rpc.port,
            api_key=cfg.rpc.api_key,
            auth_pubkey=cfg.rpc.auth_pubkey,
        )
        print(f"rpc: http://{cfg.rpc.host}:{rpc.port}", flush=True)
    if args.stake:
        node.validator_status.become_staker(int(args.stake))

    stop = asyncio.Event()

    def _sig(*_a):
        stop.set()

    loop = asyncio.get_running_loop()
    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(s, _sig)
        except NotImplementedError:
            pass

    run_task = asyncio.ensure_future(
        node.run(first_era=node.block_manager.current_height() + 1)
    )
    stop_task = asyncio.ensure_future(stop.wait())
    await asyncio.wait(
        [run_task, stop_task], return_when=asyncio.FIRST_COMPLETED
    )
    failure = None
    if run_task.done() and not run_task.cancelled():
        failure = run_task.exception()
    run_task.cancel()
    stop_task.cancel()
    await node.stop()
    if failure is not None:
        # surface the lifecycle crash: the process must exit non-zero so
        # supervisors restart it, not report success
        raise failure


CONSOLE_COMMANDS = """\
Commands:
  height                       chain tip
  block <number|latest>        block summary
  tx <hash>                    transaction
  receipt <hash>               execution receipt
  balance <0xaddr>             account balance
  nonce <0xaddr>               account nonce
  account                      the node wallet's account
  peers                        connected peer pubkeys
  validators                   current validator set
  consensus                    era/N/F/keys summary
  pool                         pending tx hashes
  phase                        cycle phase (vrf/attendance windows)
  penalty <0xaddr>             accrued attendance penalty
  metrics                      node timer/counter snapshot
  unlock <password> [seconds]  unlock the node wallet
  lock?                        wallet lock status
  send <0xto> <value>          transfer from the node wallet
  sendraw <0xhex>              submit a raw signed tx
  stake <amount>               stake from the node balance
  unstake                      request stake withdrawal
  help                         this text
  exit                         leave the console
"""


def _console_eval(call, line: str) -> object:
    """One console command -> RPC call(s). `call(method, *params)`."""
    parts = line.split()
    if not parts:
        return None
    cmd, args = parts[0].lower(), parts[1:]
    if cmd in ("help", "?"):
        return CONSOLE_COMMANDS
    if cmd == "height":
        return int(call("eth_blockNumber"), 16)
    if cmd == "block":
        tag = args[0] if args else "latest"
        if tag.isdigit():
            tag = hex(int(tag))
        return call("eth_getBlockByNumber", tag, False)
    if cmd == "tx":
        return call("eth_getTransactionByHash", args[0])
    if cmd == "receipt":
        return call("eth_getTransactionReceipt", args[0])
    if cmd == "balance":
        return int(call("eth_getBalance", args[0]), 16)
    if cmd == "nonce":
        return int(call("eth_getTransactionCount", args[0]), 16)
    if cmd == "account":
        return call("fe_account")
    if cmd == "peers":
        return call("net_peers")
    if cmd == "validators":
        return call("la_getLatestValidators")
    if cmd == "consensus":
        return call("la_consensusState")
    if cmd == "pool":
        return call("eth_getTransactionPool")
    if cmd == "phase":
        return call("fe_phase")
    if cmd == "penalty":
        addr = args[0] if args else None
        out = {"penalty": int(call("la_getPenalty", *( [addr] if addr else [] )), 16)}
        if addr:
            out.update(call("la_validatorInfo", addr))
        return out
    if cmd == "metrics":
        return call("la_metrics")
    if cmd == "unlock":
        secs = hex(int(args[1])) if len(args) > 1 else "0x12c"
        return call("fe_unlock", args[0], secs)
    if cmd == "lock?":
        return {"locked": call("fe_isLocked")}
    if cmd == "send":
        return call(
            "eth_sendTransaction", {"to": args[0], "value": hex(int(args[1]))}
        )
    if cmd == "sendraw":
        return call("eth_sendRawTransaction", args[0])
    if cmd == "stake":
        return call("validator_start_with_stake", hex(int(args[0])))
    if cmd == "unstake":
        return call("validator_stop")
    raise ValueError(f"unknown command {cmd!r} (try 'help')")


def cmd_console(args) -> int:
    import urllib.request

    def call(method, *params):
        body = json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": list(params)}
        ).encode()
        req = urllib.request.Request(
            args.rpc, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            out = json.loads(resp.read())
        if "error" in out:
            raise RuntimeError(out["error"].get("message", out["error"]))
        return out["result"]

    failures = [0]

    def run_line(line) -> bool:
        line = line.strip()
        if line in ("exit", "quit"):
            return False
        if not line:
            return True
        try:
            out = _console_eval(call, line)
            if isinstance(out, str):
                print(out)
            else:
                print(json.dumps(out, indent=2, sort_keys=True))
        except Exception as exc:  # operator tool: report, keep the shell
            failures[0] += 1
            print(f"error: {exc}", file=sys.stderr)
        return True

    if args.exec:
        for line in args.exec.split(";"):
            if not run_line(line):
                break
        # scriptable mode: a failed command must fail the invocation so
        # shell `&&` chains can react, unlike the keep-going interactive loop
        return 1 if failures[0] else 0
    try:
        import readline  # noqa: F401  (history/arrow keys when available)
    except ImportError:
        pass
    print(f"lachain-tpu console — attached to {args.rpc} ('help' for commands)")
    while True:
        try:
            line = input("lachain> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        try:
            if not run_line(line):
                return 0
        except KeyboardInterrupt:
            # ^C mid-command aborts the command, not the shell
            print("\ninterrupted", file=sys.stderr)


def cmd_trace(args) -> int:
    """Pull the node's era-lifecycle trace over RPC. Default output is
    Chrome trace_event JSON — load it in chrome://tracing or Perfetto."""
    import urllib.request

    if args.era_report or args.critical_path:
        method = "la_getEraReport"
    elif args.summary:
        method = "la_getTraceSummary"
    else:
        method = "la_getTrace"
    params = (
        []
        if args.summary or args.era_report or args.critical_path
        or args.limit is None
        else [args.limit]
    )
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        args.rpc, data=body, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=args.timeout) as resp:
        out = json.loads(resp.read())
    if "error" in out:
        print(f"error: {out['error'].get('message', out['error'])}",
              file=sys.stderr)
        return 1
    result = out["result"]
    if args.era_report or args.critical_path:
        from .utils import tracing

        if args.era_report:
            print(tracing.era_report_table(result))
        if args.critical_path:
            print(tracing.critical_path_table(result))
        reported = result.get("eras", [])
        if reported and args.out:
            with open(args.out, "w") as fh:
                fh.write(json.dumps(result, indent=2))
            print(f"era report -> {args.out}")
        return 0
    if args.summary:
        print(json.dumps(result, indent=2, sort_keys=True))
        return 0
    text = json.dumps(result)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
        print(
            f"{len(result.get('traceEvents', []))} events -> {args.out} "
            "(open in chrome://tracing or https://ui.perfetto.dev)"
        )
    else:
        print(text)
    return 0


def cmd_fleet_trace(args) -> int:
    """Scrape N nodes' traces/era reports/health over RPC, align their
    clocks by RTT-bracketed la_time pings, and write ONE merged Chrome
    trace with a pid lane block per node. Searching the merged trace for
    a sampled tx's 16-hex-char trace id (la_getTxTrace -> traceId) lights
    up its lifecycle across every node that touched it."""
    from .utils import fleetview

    names = args.names.split(",") if args.names else None
    if names is not None and len(names) != len(args.rpc):
        print("error: --names count must match --rpc count", file=sys.stderr)
        return 1
    merged, report = fleetview.collect(
        args.rpc,
        names=names,
        samples=args.samples,
        timeout=args.timeout,
        api_key=args.api_key,
    )
    unreachable = [
        n["name"]
        for n in merged["fleet"]["nodes"]
        if n["errors"].get("trace") and n["errors"].get("eraReport")
    ]
    if unreachable:
        print(
            f"warning: no data from {', '.join(unreachable)}",
            file=sys.stderr,
        )
        if len(unreachable) == len(args.rpc):
            print("error: every node unreachable", file=sys.stderr)
            return 1
    print(fleetview.fleet_era_table(report))
    for n in merged["fleet"]["nodes"]:
        status = n["status"] or "?"
        unc = n["uncertaintyUs"]
        rtt = n.get("rttMaxMs")
        wirev = n.get("wireVersion")
        print(
            f"{n['name']}: status={status} "
            f"offset={n['offsetUs'] or 0:.0f}us"
            + (f" (±{unc:.0f}us)" if unc is not None else "")
            + (f" rtt_max={rtt:.0f}ms" if rtt is not None else "")
            + (f" wire=v{wirev}" if wirev is not None else "")
        )
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(json.dumps(merged))
        n_events = sum(
            1 for e in merged["traceEvents"] if e.get("ph") != "M"
        )
        print(
            f"{n_events} events from {len(args.rpc)} nodes -> {args.out} "
            "(open in chrome://tracing or https://ui.perfetto.dev)"
        )
    return 0


def cmd_chaos(args) -> int:
    """Seeded fault-injection run: an in-process devnet pushed through
    `--eras` eras under a FaultPlan, printing an era/recovery report.
    Exit 0 iff every era decided identically on every node."""
    import time

    from .core.devnet import Devnet
    from .network.faults import FaultPlan
    from .utils import metrics

    if args.crash_point:
        # storage crash scenario: orthogonal to the network fault plan (a
        # SIGKILLed child + fsck + resume, not an in-process devnet)
        return _run_crash_point_scenario(args)
    adversary = None
    if args.byzantine:
        from .consensus.adversary import AdversaryPlan

        traitors = (
            tuple(int(t) for t in args.traitors.split(","))
            if args.traitors
            else tuple(range(args.f))
        )
        try:
            adversary = AdversaryPlan(
                strategy=args.byzantine, traitors=traitors, seed=args.seed
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    shaper = None
    if getattr(args, "wan", None):
        from .network.faults import LinkShaper

        try:
            shaper = LinkShaper.parse(args.wan)
        except ValueError as e:
            print(f"error: bad --wan spec: {e}", file=sys.stderr)
            return 2
    plan = FaultPlan(
        seed=args.seed,
        drop=args.drop,
        duplicate=args.duplicate,
        delay=args.delay,
        reorder=args.reorder,
        crashes=tuple(FaultPlan.parse_crash(s) for s in args.crash),
        partitions=tuple(
            FaultPlan.parse_partition(s) for s in args.partition
        ),
        shaper=shaper,
    )
    print(
        f"chaos: n={args.n} f={args.f} eras={args.eras} seed={args.seed} "
        f"engine={args.engine}"
    )
    print(
        f"plan: drop={plan.drop} duplicate={plan.duplicate} "
        f"delay={plan.delay} reorder={plan.reorder} "
        f"crashes={len(plan.crashes)} partitions={len(plan.partitions)}"
        + (f" wan={args.wan}" if shaper is not None else "")
    )
    if adversary is not None:
        print(
            f"byzantine: strategy={adversary.strategy} "
            f"traitors={list(adversary.traitors)} seed={adversary.seed}"
        )
    try:
        net = Devnet(
            n=args.n,
            f=args.f,
            seed=args.seed,
            fault_plan=plan,
            engine=args.engine,
            adversary=adversary,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    failures = 0
    for era in range(1, args.eras + 1):
        t0 = time.perf_counter()
        delivered0 = net.net.delivered_count
        recov0 = getattr(net.net, "recovery_rounds", 0)
        try:
            blocks = net.run_era(era)
        except RuntimeError as e:
            failures += 1
            print(f"era {era:>3}: FAILED ({e})")
            continue
        dt = time.perf_counter() - t0
        era_ev = ""
        if adversary is not None:
            from .consensus.evidence import era_counts

            counts = era_counts(era)
            era_ev = (
                f" equivocations={counts.get('equivocation', 0)}"
                f" invalid_shares={counts.get('invalid_share', 0)}"
            )
        print(
            f"era {era:>3}: block {blocks[0].hash().hex()[:16]} "
            f"msgs={net.net.delivered_count - delivered0} "
            f"recovery_rounds={getattr(net.net, 'recovery_rounds', 0) - recov0} "
            f"{dt:.2f}s{era_ev}"
        )
    faults = getattr(net.net, "faults", None)
    if faults is not None:
        print("fault report:", json.dumps(faults.stats, sort_keys=True))
    replayed = metrics.counter_value("consensus_outbox_replayed_total")
    evicted = metrics.counter_value("consensus_outbox_evicted_total")
    print(
        f"recovery report: recovery_rounds="
        f"{getattr(net.net, 'recovery_rounds', 0)} "
        f"outbox_replayed={int(replayed)} outbox_evicted={int(evicted)}"
    )
    if adversary is not None:
        # evidence identity: honest nodes must have detected the SAME set
        honest = [
            i for i in range(args.n) if i not in adversary.traitors
        ]
        sets = [net.net.routers[i].evidence.record_set() for i in honest]
        shed = metrics.counter_value(
            "consensus_msgs_shed_total", labels={"reason": "latch_cap"}
        )
        print(
            f"byzantine report: evidence_records={len(sets[0])} "
            f"evidence_identical={all(s == sets[0] for s in sets)} "
            f"latch_shed={int(shed)}"
        )
        for rec in net.net.routers[honest[0]].evidence.snapshot():
            print(f"  evidence: {json.dumps(rec, sort_keys=True)}")
    heights = [net.height(i) for i in range(args.n)]
    print(f"heights: {heights}")
    if failures or len(set(heights)) != 1:
        print("CHAOS RUN FAILED", file=sys.stderr)
        return 1
    print(f"ok: {args.eras} eras survived the plan")
    return 0


def cmd_fleet_upgrade(args) -> int:
    """Zero-downtime rolling-upgrade drill: boot an n-node loopback TCP
    fleet on the legacy (pre-handshake) wire, optionally WAN-shaped into
    emulated regions, then roll every node one at a time onto the
    upgraded wire while the survivors keep committing eras under paced
    open-loop traffic. Gates: /healthz stays `ok` on every live node at
    every era checkpoint and the FLEET misses zero eras (a rolling node
    sitting one out is the expected shape). Prints a compare.py-readable
    JSON result line (era_latency_p99_s + rtt_ms)."""
    import random
    import time

    from .core.fleet import TcpFleet
    from .core.types import Transaction, sign_transaction
    from .crypto import ecdsa
    from .network import wire
    from .network.faults import LinkShaper

    shaper = None
    if args.wan:
        try:
            shaper = LinkShaper.parse(args.wan)
        except ValueError as e:
            print(f"error: bad --wan spec: {e}", file=sys.stderr)
            return 2

    class _Rng:
        def __init__(self, seed):
            self._r = random.Random(seed)

        def randbelow(self, k):
            return self._r.randrange(k)

    async def drill() -> int:
        user_priv = ecdsa.generate_private_key(_Rng(args.seed + 1))
        user_addr = ecdsa.address_from_public_key(
            ecdsa.public_key_bytes(user_priv)
        )
        fleet = TcpFleet(
            n=args.n,
            f=args.f,
            seed=args.seed,
            txs_per_block=max(128, args.txs_per_era),
            initial_balances={user_addr: 10**24},
            shaper=shaper,
            legacy_wire=True,
            era_timeout=args.era_timeout,
        )
        era = 0
        nonce = 0
        era_lat: List[float] = []
        failures: List[str] = []

        async def one_era() -> None:
            nonlocal era, nonce
            era += 1
            txs = [
                sign_transaction(
                    Transaction(
                        to=b"\x0d" * 20,
                        value=1,
                        nonce=nonce + j,
                        gas_price=1,
                        gas_limit=21000,
                    ),
                    user_priv,
                    fleet.chain_id,
                )
                for j in range(args.txs_per_era)
            ]
            nonce += args.txs_per_era
            await fleet.submit_and_settle(txs)
            t0 = time.perf_counter()
            h = await fleet.run_era(era)
            era_lat.append(time.perf_counter() - t0)
            bad = {
                i: s for i, s in fleet.health_statuses().items() if s != "ok"
            }
            if bad:
                failures.append(f"era {era}: health left ok: {bad}")
            print(
                f"era {era:>3}: {h.hex()[:16]} {era_lat[-1]:.2f}s "
                f"health={'ok' if not bad else bad} rtt_ms={fleet.rtt_ms()}"
            )

        await fleet.start()
        try:
            for _ in range(args.warmup):
                await one_era()
            for i in range(args.n):
                await fleet.take_down(i)
                region = fleet.region_of(i) or "-"
                print(f"roll: node {i} down (region {region})")
                await one_era()  # survivors commit with node i out
                await fleet.bring_up(i, next_era=era + 1)
                print(
                    f"roll: node {i} back on wire v{fleet.wire_versions()[i]}"
                )
            for _ in range(args.cooldown):
                await one_era()
            rtt = fleet.rtt_ms()
            versions = fleet.wire_versions()
        finally:
            await fleet.stop()
        if any(v != wire.WIRE_VERSION for v in versions.values()):
            failures.append(f"nodes left on the old wire: {versions}")
        lat = sorted(era_lat)
        result = {
            "metric": "fleet_upgrade",
            "n": args.n,
            "f": args.f,
            "eras": era,
            "rolled": args.n,
            "era_latency_p50_s": round(lat[len(lat) // 2], 4),
            "era_latency_p99_s": round(
                lat[min(len(lat) - 1, int(0.99 * len(lat)))], 4
            ),
            "rtt_ms": rtt,
            "wire_versions": {str(k): v for k, v in versions.items()},
            "healthz_ok": not failures,
            "wan": args.wan or "",
        }
        print(json.dumps(result, sort_keys=True))
        if failures:
            for msg in failures:
                print(msg, file=sys.stderr)
            print("FLEET-UPGRADE DRILL FAILED", file=sys.stderr)
            return 1
        print(
            f"ok: rolled {args.n}/{args.n} nodes, zero fleet missed eras, "
            f"/healthz ok throughout"
        )
        return 0

    return asyncio.run(drill())


def cmd_run(args) -> int:
    from .core.config import NodeConfig

    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    cfg = NodeConfig.load(args.config)
    try:
        asyncio.run(_run_node(cfg, args))
    except KeyboardInterrupt:
        pass
    return 0


def cmd_height(args) -> int:
    from .core.config import NodeConfig

    cfg = NodeConfig.load(args.config)
    node, _ = _build_node(cfg, args.config)
    print(
        json.dumps(
            {
                "height": node.block_manager.current_height(),
                # the committed state root: the --expect-root value for
                # db import and the operator's cross-node consistency check
                "stateHash": node.state.committed.state_hash().hex(),
                "chainId": node.chain_id,
                "validators": node.public_keys.n,
            }
        )
    )
    return 0


_DB_DUMP_MAGIC = b"LKVD0001"


def cmd_db(args) -> int:
    """Offline database maintenance: shrink (prune old trie checkpoints),
    rollback (restore an older snapshot) — reference `lachain db` verbs
    + --RollBackTo (Program.cs:25-39, Application.cs:119-127) — plus
    compact (LSM full merge), and export/import (engine-portable dump;
    the supported migration path between storage engines, since sqlite and
    LSM on-disk formats are not interchangeable). The node must be
    STOPPED: these operations mutate or snapshot the store
    non-transactionally with respect to concurrent commits."""
    from .core.config import NodeConfig
    from .storage.kv import SqliteKV
    from .storage.lsm import LsmKV
    from .storage.shrink import DbShrink
    from .storage.state import StateManager

    cfg = NodeConfig.load(args.config)
    db_path = cfg.storage_path or (
        os.path.splitext(args.config)[0] + ".db"
    )
    make_kv = LsmKV if cfg.storage_engine == "lsm" else SqliteKV

    if args.db_cmd == "import":
        # target must be FRESH: importing over live state would interleave
        # two chains' keys into one store
        if os.path.exists(db_path):
            print(f"refusing import: {db_path} already exists", file=sys.stderr)
            return 1
        count = 0
        kv = make_kv(db_path)
        try:
            with open(args.dump, "rb") as fh:
                if fh.read(len(_DB_DUMP_MAGIC)) != _DB_DUMP_MAGIC:
                    print(f"{args.dump}: not a db export", file=sys.stderr)
                    return 1
                batch = []
                while True:
                    head = fh.read(4)
                    if not head:
                        break
                    klen = int.from_bytes(head, "little")
                    k = fh.read(klen)
                    vlen = int.from_bytes(fh.read(4), "little")
                    v = fh.read(vlen)
                    if len(k) != klen or len(v) != vlen:
                        print(f"{args.dump}: truncated", file=sys.stderr)
                        return 1
                    batch.append((k, v))
                    count += 1
                    if len(batch) >= 2000:
                        kv.write_batch(batch)
                        batch = []
                if batch:
                    kv.write_batch(batch)
            # migration/snapshot contract: a dump is not self-certifying.
            # The imported tip's state roots must hash to the operator-
            # supplied --expect-root (read from a trusted block header);
            # without the flag a non-empty import is refused outright.
            from .storage.fsck import verify_imported_state

            expect = getattr(args, "expect_root", None)
            expect_hash = (
                bytes.fromhex(expect.removeprefix("0x")) if expect else None
            )
            problem = (
                verify_imported_state(kv, expect_hash) if count else None
            )
        finally:
            kv.close()
        if problem is not None:
            # remove the refused store so a corrected re-run is not
            # blocked by the freshness check above
            import shutil

            if os.path.isdir(db_path):
                shutil.rmtree(db_path, ignore_errors=True)
            elif os.path.exists(db_path):
                os.remove(db_path)
            print(f"import verification failed: {problem}", file=sys.stderr)
            return 1
        print(json.dumps({"imported": count, "engine": cfg.storage_engine,
                          "verifiedRoot": expect or None}))
        return 0

    if not os.path.exists(db_path):
        print(f"no database at {db_path}", file=sys.stderr)
        return 1
    # same engine switch as the node itself: maintenance verbs must open
    # the store the node actually wrote
    kv = make_kv(db_path)
    try:
        if args.db_cmd == "shrink":
            state = StateManager(kv)
            stats = DbShrink(state, kv).shrink(args.retain)
            print(json.dumps(stats))
        elif args.db_cmd == "rollback":
            state = StateManager(kv)
            height = args.height
            old = state.committed_height()
            try:
                state.rollback_to(height)
            except KeyError as e:
                print(str(e), file=sys.stderr)
                return 1
            print(
                json.dumps({"rolledBackFrom": old, "height": height})
            )
        elif args.db_cmd == "compact":
            if not isinstance(kv, LsmKV):
                print("compact: only the lsm engine", file=sys.stderr)
                return 1
            before = kv.table_count()
            kv.compact()
            print(json.dumps(
                {"tablesBefore": before, "tablesAfter": kv.table_count(),
                 "stats": kv.stats()}
            ))
        elif args.db_cmd == "export":
            count = 0
            with open(args.out, "wb") as fh:
                fh.write(_DB_DUMP_MAGIC)
                for k, v in kv.scan_prefix(b""):
                    fh.write(len(k).to_bytes(4, "little") + k)
                    fh.write(len(v).to_bytes(4, "little") + v)
                    count += 1
            print(json.dumps({"exported": count, "path": args.out}))
    finally:
        kv.close()
    return 0


def cmd_fsck(args) -> int:
    """Storage invariant scan (storage/fsck.py): the standalone verb for
    what the node runs on every open. Exit codes: 0 clean-or-repaired,
    1 refused (fatal issues — see the DEPLOY.md runbook), 2 no database."""
    from .core.config import NodeConfig
    from .storage.fsck import fsck
    from .storage.kv import SqliteKV
    from .storage.lsm import LsmKV

    cfg = NodeConfig.load(args.config)
    db_path = cfg.storage_path or (
        os.path.splitext(args.config)[0] + ".db"
    )
    if not os.path.exists(db_path):
        print(f"no database at {db_path}", file=sys.stderr)
        return 2
    kv = (LsmKV if cfg.storage_engine == "lsm" else SqliteKV)(db_path)
    try:
        report = fsck(kv, repair=not args.no_repair, deep=args.deep)
    finally:
        kv.close()
    print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    return 1 if report.fatal else 0


def _run_crash_point_scenario(args) -> int:
    """chaos --crash-point: SIGKILL a real child process at a named storage
    pipeline point, then prove the recovery story — fsck detects/repairs
    the torn state and a resumed run completes. Repeating the same spec is
    deterministic: the report prints the final chain height both times."""
    import subprocess
    import tempfile

    from .storage import crash_workload, crashpoints
    from .storage.fsck import fsck

    specs = []
    for spec in args.crash_point:
        point = crashpoints.CrashPlan.parse_point(spec)
        # the child must genuinely die: force sigkill mode
        specs.append(
            crashpoints.CrashPoint(
                name=point.name, hit=point.hit, mode=crashpoints.MODE_SIGKILL
            )
        )
    plan = crashpoints.CrashPlan(points=tuple(specs))
    print(f"chaos crash-point: plan={plan.encode_env()} engine={args.engine}")
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        db_path = os.path.join(tmp, "chaos.db")
        env = dict(os.environ)
        env[crashpoints.ENV_VAR] = plan.encode_env()
        env.setdefault("JAX_PLATFORMS", "cpu")
        child = subprocess.run(
            [
                sys.executable,
                "-m",
                "lachain_tpu.storage.crash_workload",
                db_path,
                args.engine,
            ],
            env=env,
            capture_output=True,
            text=True,
        )
        killed = child.returncode == -signal.SIGKILL
        print(
            f"child: rc={child.returncode} "
            f"({'SIGKILLed at plan point' if killed else 'ran to completion'})"
        )
        if not killed:
            print(
                "crash point never fired — the workload does not traverse "
                f"{[p.name for p in plan.points]}",
                file=sys.stderr,
            )
            return 1
        kv = crash_workload.open_kv(db_path, args.engine)
        try:
            report = fsck(kv, repair=True)
            print("fsck:", json.dumps(report.to_dict(), sort_keys=True))
            if report.fatal:
                failures += 1
            recheck = fsck(kv, repair=False)
            if recheck.fatal:
                print("fsck recheck still fatal after repair", file=sys.stderr)
                failures += 1
            # resume: the workload continues from the committed tip
            stats = crash_workload.run_workload(kv)
            print("resumed run:", json.dumps(stats, sort_keys=True))
            if stats["height"] != crash_workload.DEFAULT_BLOCKS:
                failures += 1
        finally:
            kv.close()
    if failures:
        print("CHAOS CRASH-POINT RUN FAILED", file=sys.stderr)
        return 1
    print("ok: crashed, repaired, resumed")
    return 0


def cmd_encrypt(args) -> int:
    """Password-protect (or re-key) a wallet file in place
    (reference `lachain encrypt`, Program.cs:25-39)."""
    from .core.vault import PrivateWallet

    old_pw = args.old_password or os.environ.get(
        "LACHAIN_WALLET_PASSWORD", ""
    )
    wallet = PrivateWallet.load(args.wallet, old_pw)
    wallet.set_password(args.password)
    wallet.save(args.wallet)
    print(json.dumps({"wallet": args.wallet, "encrypted": bool(args.password)}))
    return 0


def cmd_decrypt(args) -> int:
    """Print a wallet's decrypted JSON to stdout (reference
    `lachain decrypt`) — for operator inspection/backup; keys go to the
    terminal, so use deliberately."""
    from .core.vault import PrivateWallet

    pw = args.password or os.environ.get("LACHAIN_WALLET_PASSWORD", "")
    wallet = PrivateWallet.load(args.wallet, pw)
    print(wallet.to_json())
    return 0


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="lachain-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    kg = sub.add_parser("keygen", help="generate a trusted-dealer devnet")
    kg.add_argument("--n", type=int, required=True)
    kg.add_argument("--f", type=int, required=True)
    kg.add_argument("--out", required=True)
    kg.add_argument("--host", default="127.0.0.1")
    kg.add_argument("--port-base", type=int, default=7070)
    kg.add_argument("--chain-id", type=int, default=225)
    kg.add_argument("--cycle-duration", type=int, default=1000)
    kg.add_argument("--vrf-phase", type=int, default=500)
    kg.add_argument("--initial-balance", type=int, default=10**24)
    kg.add_argument("--block-time-ms", type=int, default=1000)
    kg.add_argument(
        "--fund", nargs="*", help="extra 0x addresses to fund at genesis"
    )
    kg.add_argument(
        "--encrypt", action="store_true", help="password-protect wallets"
    )
    kg.add_argument(
        "--regions",
        metavar="R1,R2,...",
        help="stripe nodes round-robin across these emulated regions "
             "(written as network.region; node i gets region i %% len)",
    )
    kg.add_argument(
        "--wan",
        metavar="SPEC",
        help="LinkShaper spec written to every config's network.wanShaper, "
             "e.g. 'regions=us,eu,ap,sa;default=80ms/8ms@4mbps;intra=2ms'",
    )
    kg.set_defaults(fn=cmd_keygen)

    rn = sub.add_parser("run", help="run a node from a config")
    rn.add_argument("--config", required=True)
    rn.add_argument("--stake", help="stake this amount at startup")
    rn.add_argument(
        "--fast-sync",
        action="store_true",
        help="download state from the configured peers instead of "
        "replaying blocks (multi-peer, with failover)",
    )
    rn.add_argument(
        "--snapshot",
        action="store_true",
        help="with --fast-sync: bulk-import a snapshot stream first, "
        "then trie-walk only the diff",
    )
    rn.add_argument(
        "--trusted-checkpoint",
        metavar="HEIGHT:BLOCKHASH",
        help="with --fast-sync: accept the target block by this "
        "checkpoint instead of a genesis-validator multisig quorum "
        "(required once the chain has rotated validators)",
    )
    rn.set_defaults(fn=cmd_run)

    ht = sub.add_parser("height", help="print local chain status")
    ht.add_argument("--config", required=True)
    ht.set_defaults(fn=cmd_height)

    db = sub.add_parser("db", help="offline database maintenance")
    dbsub = db.add_subparsers(dest="db_cmd", required=True)
    sh = dbsub.add_parser("shrink", help="prune old trie checkpoints")
    sh.add_argument("--config", required=True)
    sh.add_argument("--retain", type=int, default=1000,
                    help="checkpoint depth to keep below the tip")
    sh.set_defaults(fn=cmd_db)
    rb = dbsub.add_parser("rollback", help="restore an older snapshot")
    rb.add_argument("--config", required=True)
    rb.add_argument("--height", type=int, required=True)
    rb.set_defaults(fn=cmd_db)
    cp = dbsub.add_parser(
        "compact", help="full LSM merge to a single table (lsm engine only)"
    )
    cp.add_argument("--config", required=True)
    cp.set_defaults(fn=cmd_db)
    ex = dbsub.add_parser(
        "export", help="dump every key/value to an engine-portable file"
    )
    ex.add_argument("--config", required=True)
    ex.add_argument("--out", required=True)
    ex.set_defaults(fn=cmd_db)
    im = dbsub.add_parser(
        "import",
        help="load an export into a FRESH store of the configured engine "
             "(the sqlite<->lsm migration path)",
    )
    im.add_argument("--config", required=True)
    im.add_argument("--dump", required=True)
    im.add_argument(
        "--expect-root",
        help="state hash (hex) from a trusted block header that the "
        "imported tip must match; without it a non-empty import is "
        "refused — the dump is never trusted blindly",
    )
    im.set_defaults(fn=cmd_db)

    en = sub.add_parser("encrypt", help="password-protect a wallet file")
    en.add_argument("--wallet", required=True)
    en.add_argument("--password", required=True)
    en.add_argument("--old-password", default=None)
    en.set_defaults(fn=cmd_encrypt)

    co = sub.add_parser(
        "console", help="interactive operator shell over a live node's RPC"
    )
    co.add_argument("--rpc", default="http://127.0.0.1:7071")
    co.add_argument("--timeout", type=float, default=10.0)
    co.add_argument(
        "--exec",
        help="run ';'-separated commands non-interactively and exit",
    )
    co.set_defaults(fn=cmd_console)

    tr = sub.add_parser(
        "trace",
        help="pull the node's era-lifecycle trace (Chrome trace_event JSON)",
    )
    tr.add_argument("--rpc", default="http://127.0.0.1:7071")
    tr.add_argument("--timeout", type=float, default=10.0)
    tr.add_argument("--out", help="write the trace JSON to this file")
    tr.add_argument(
        "--limit", type=int, default=None, help="cap the event count"
    )
    tr.add_argument(
        "--summary",
        action="store_true",
        help="print the per-span aggregate instead of the full trace",
    )
    tr.add_argument(
        "--era-report",
        action="store_true",
        help="print the per-era phase table (propose/RBC/BA/coin/TPKE/"
        "commit + idle split into wait buckets) from the merged flight "
        "recorder",
    )
    tr.add_argument(
        "--critical-path",
        action="store_true",
        help="print each era's longest blocking chain (phase and wait "
        "segments from era start to commit) from the merged flight "
        "recorder",
    )
    tr.set_defaults(fn=cmd_trace)

    ft = sub.add_parser(
        "fleet-trace",
        help="merge N nodes' traces into one clock-aligned Chrome trace "
        "with per-node lanes, plus the fleet era/skew table",
    )
    ft.add_argument(
        "--rpc",
        nargs="+",
        required=True,
        help="one RPC URL per node, e.g. http://10.0.0.1:7070",
    )
    ft.add_argument(
        "--names",
        help="comma-separated node labels matching --rpc order "
        "(default node0..nodeN-1)",
    )
    ft.add_argument("--timeout", type=float, default=10.0)
    ft.add_argument(
        "--samples",
        type=int,
        default=5,
        help="la_time pings per node for clock alignment",
    )
    ft.add_argument("--api-key", help="x-api-key if the RPC is gated")
    ft.add_argument("--out", help="write the merged trace JSON here")
    ft.set_defaults(fn=cmd_fleet_trace)

    de = sub.add_parser("decrypt", help="print a wallet's decrypted JSON")
    de.add_argument("--wallet", required=True)
    de.add_argument("--password", default=None)
    de.set_defaults(fn=cmd_decrypt)

    ch = sub.add_parser(
        "chaos",
        help="run a seeded fault scenario against an in-process devnet",
    )
    ch.add_argument("--n", type=int, default=4)
    ch.add_argument("--f", type=int, default=1)
    ch.add_argument("--eras", type=int, default=3)
    ch.add_argument("--seed", type=int, default=0)
    ch.add_argument("--drop", type=float, default=0.0,
                    help="per-message loss probability")
    ch.add_argument("--duplicate", type=float, default=0.0,
                    help="per-message duplication probability")
    ch.add_argument("--delay", type=float, default=0.0,
                    help="per-message delay probability")
    ch.add_argument("--reorder", type=float, default=0.0,
                    help="per-message reorder probability")
    ch.add_argument("--crash", action="append", default=[],
                    metavar="NODE@AT[:RESTART]",
                    help="crash schedule, repeatable (e.g. 3@50:400)")
    ch.add_argument("--partition", action="append", default=[],
                    metavar="A,B|C,D@AT[:HEAL]",
                    help="partition schedule, repeatable "
                         "(e.g. '0,1|2,3@30:500')")
    ch.add_argument("--engine", choices=["python", "native", "sqlite", "lsm"],
                    default="python",
                    help="consensus engine for fault runs; storage engine "
                         "(sqlite|lsm) for --crash-point runs")
    ch.add_argument("--crash-point", action="append", default=[],
                    metavar="NAME[@HIT]",
                    help="storage crash scenario: SIGKILL a child workload "
                         "at this pipeline point (see storage/crashpoints.py"
                         " for names), then fsck + resume; repeatable")
    ch.add_argument("--byzantine", default=None,
                    metavar="STRATEGY",
                    choices=["equivocate", "withhold", "relay", "spam",
                             "equivocate_votes"],
                    help="smart-malicious traitors (consensus/adversary.py):"
                         " equivocate (conflicting coin/TPKE shares per"
                         " slot), withhold (shares to only f+1 seeded"
                         " recipients), relay (seeded replay of captured"
                         " signed frames, spoofed origin), spam (flood"
                         " distinct coin slots past the latch budget),"
                         " equivocate_votes (AUX/CONF flip, python engine"
                         " only); prints per-era evidence + recovery report")
    ch.add_argument("--traitors", default=None,
                    metavar="I,J,...",
                    help="comma-separated traitor ids for --byzantine "
                         "(default: validators 0..f-1)")
    ch.add_argument("--wan", default=None, metavar="SPEC",
                    help="LinkShaper WAN matrix on every link (python "
                         "engine only), e.g. 'regions=us,eu;"
                         "default=40ms/5ms;intra=2ms;burst=0.01x8'")
    ch.set_defaults(fn=cmd_chaos)

    fu = sub.add_parser(
        "fleet-upgrade",
        help="zero-downtime rolling-upgrade drill: legacy-wire TCP fleet "
             "rolled node-by-node onto the LTRX wire under traffic, gated "
             "on /healthz + zero fleet missed eras",
    )
    fu.add_argument("--n", type=int, default=6)
    fu.add_argument("--f", type=int, default=1)
    fu.add_argument("--seed", type=int, default=0)
    fu.add_argument("--wan", default=None, metavar="SPEC",
                    help="LinkShaper spec shaping the fleet's loopback "
                         "links into emulated regions")
    fu.add_argument("--txs-per-era", type=int, default=8,
                    help="open-loop transactions paced in before each era")
    fu.add_argument("--warmup", type=int, default=1,
                    help="eras committed before the roll starts")
    fu.add_argument("--cooldown", type=int, default=1,
                    help="eras committed after every node is upgraded")
    fu.add_argument("--era-timeout", type=float, default=60.0)
    fu.set_defaults(fn=cmd_fleet_upgrade)

    fs = sub.add_parser(
        "fsck", help="scan storage invariants; repair or refuse"
    )
    fs.add_argument("--config", required=True)
    fs.add_argument("--deep", action="store_true",
                    help="full trie DFS + full index scans (slow)")
    fs.add_argument("--no-repair", action="store_true",
                    help="report only; repairable issues become fatal")
    fs.set_defaults(fn=cmd_fsck)

    args = p.parse_args(argv)
    # subprocess crash harness: a child `lachain-tpu run` executes the
    # parent's CrashPlan (no-op unless LACHAIN_CRASH_POINTS is set)
    from .storage.crashpoints import arm_from_env

    arm_from_env()
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
